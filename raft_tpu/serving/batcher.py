"""Dynamic micro-batcher: coalesce, pad, execute, deliver — and contain.

One daemon thread pulls same-bucket FIFO runs from the admission queue
(``RequestQueue.take_batch``: full batch, aged ``max_wait_ms``, or drain —
whichever first), pads the group up to the next declared batch step by
repeating the last pair (any filler works — per-sample inference is
independent; repetition keeps values finite for the instance norms), runs
the warm engine, slices real rows back out, unpads each to its request's
original resolution, and resolves the waiting handler threads.

The engine is injected as a callable ``run(bucket, im1, im2) -> flow`` so
tests can drive the batching policy with a stub (slow / counting / failing)
engine and never touch a compile.

Failure containment (SERVING.md "Failure modes & degradation ladder"):

* **Non-finite sentinel** — every flow output is row-checked host-side;
  a NaN/Inf row fails only ITS request (HTTP 500, status ``poisoned``,
  ``raft_nonfinite_outputs_total``) while co-batched neighbors resolve.
* **Poisoned-batch bisection** — an engine exception is first retried
  (transient device errors heal under backoff), then the batch is
  split-and-retried so only the guilty request fails with
  :class:`PoisonedRequest`; innocents succeed.  Sub-groups pad to the
  declared batch steps, so bisection never compiles a new shape.  Total
  engine calls per batch are capped by a budget (~2x the group size per
  attempt), so a sick engine cannot trap the thread in retry storms.
* **Crash surface** — an exception escaping the loop itself fails any
  in-flight requests and is handed to the server's supervisor, which
  restarts the thread (``server.BatcherSupervisor``).  KeyboardInterrupt/
  SystemExit are re-raised after failing the batch, never swallowed.

Streaming steps (serving/stream.py) share this thread — ONE owner of the
device.  Session OPENS execute solo via the injected ``stream_fn`` (the
queue keys them per session id: an open runs the encode executable and
has nothing to coalesce with); ADVANCES key per bucket and coalesce
across *different* sessions exactly like pairwise work — a popped run
of them goes to ``stream_group_fn`` (the coordinator's continuous-
batched step: one device call advances the whole group, per-row
non-finite sentinel + degrade-to-cold heal inside).  A popped run is
always homogeneous: all-pairwise, all-advances (one bucket), or one
open — the keys guarantee it.

Thread model (SERVING.md "Threading model"): the batcher deliberately
holds **no lock of its own** — single ownership IS its synchronization.
``batches``/``served``/``timed_out`` and ``_inflight_batch`` are written
only on the loop thread (``restart()`` builds a new thread only after
the old one has died, so single-writer holds across restarts); other
threads only ever read them (serve_cli's exit line, /healthz, tests),
which is why raftlint's C1/C6 — scoped to lock-HOLDING classes — do not
apply here.  Everything shared it touches synchronizes on the owner's
lock: the queue's (take_batch), the breaker's (record), the store's
(attach/demote, inside stream_fn) — always one at a time, so the
batcher thread can never hold two locks and can never deadlock.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..data.pipeline import unpad
from ..telemetry import events as tlm_events
from ..telemetry import spans as tlm_spans
from .queue import DeadlineExceeded, RequestQueue


class PoisonedRequest(RuntimeError):
    """The bisected-guilty request of a failing batch: the engine fails
    whenever this request is present, after retries (HTTP 500, error
    class ``poisoned``)."""
    trace_status = tlm_spans.POISONED


class NonFiniteOutput(RuntimeError):
    """The engine produced NaN/Inf flow for this request's row (HTTP 500,
    error class ``poisoned``) — inputs were validated at the HTTP edge
    (http.py), so a non-finite *output* is an engine-side failure."""
    trace_status = tlm_spans.POISONED


class BatcherCrashed(RuntimeError):
    """The batcher thread died while this request was in flight; the
    supervisor restarts the loop — retry the request."""
    trace_status = tlm_spans.ERROR


def _fresh_error(e: BaseException) -> BaseException:
    """Clone a group-wide failure per waiter: the HTTP layer stamps the
    request's trace id onto the exception it receives, so a SHARED
    instance would cross-wire ids between co-batched clients.  A
    constructor that rejects its own args (kwarg-only shutdown wrappers)
    falls back to the shared instance — still a correct failure;
    stamp-if-absent keeps the first trace id."""
    try:
        return type(e)(*e.args)
    except Exception:
        return e


class MicroBatcher:
    def __init__(self, queue: RequestQueue, run_fn: Callable,
                 pad_batch_to: Callable[[int], int], max_batch: int,
                 max_wait_ms: float, metrics: Optional[Dict] = None,
                 stream_fn: Optional[Callable] = None,
                 stream_group_fn: Optional[Callable] = None,
                 breaker=None, faults=None, retries: int = 1,
                 retry_backoff_s: float = 0.02, on_crash=None,
                 ragged: bool = False, ragged_batch_pixels: int = 0):
        self.queue = queue
        self.run_fn = run_fn
        # ragged mixed-resolution mode (SERVING.md "Ragged serving"):
        # every pairwise request is queued under the shared max-box
        # bucket (so the FIFO coalesces across resolutions for free) and
        # run_fn is called with a 4th arg — per-row [b, 2] int32 live
        # sizes built from each request's routed bucket (Request.rbucket).
        # ragged_batch_pixels > 0 bounds one device batch's LIVE-pixel
        # footprint: a popped run is greedily chunked so co-batched live
        # pixels never exceed the budget (a 1080p row can't starve a
        # group of thumbnails); 0 = unbounded.
        self.ragged = ragged
        self.ragged_batch_pixels = ragged_batch_pixels
        # streaming steps (serving/stream.py) ride the same queue and the
        # same device-owning thread: stream_fn takes ONE StreamRequest
        # (session open / solo fallback) and returns (padded flow or
        # None, iters_used or None); stream_group_fn takes a coalesced
        # LIST of same-bucket advances and returns per-row
        # (flow, iters_used, err) tuples (the continuous-batched path)
        self.stream_fn = stream_fn
        self.stream_group_fn = stream_group_fn
        self.pad_batch_to = pad_batch_to
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        self.metrics = metrics or {}
        self.breaker = breaker            # CircuitBreaker or None
        self.faults = faults              # FaultInjector or None (chaos)
        self.retries = retries            # same-group retries before bisect
        self.retry_backoff_s = retry_backoff_s
        self.on_crash = on_crash          # supervisor hook: (exception) ->
        self.batches = 0
        self.served = 0
        self.timed_out = 0
        self._inflight_batch = None       # the popped-but-unresolved batch
        self._thread = self._new_thread()

    def _new_thread(self) -> threading.Thread:
        return threading.Thread(target=self._thread_main, daemon=True,
                                name="raft-serving-batcher")

    def start(self) -> None:
        self._thread.start()

    def restart(self) -> None:
        """Supervisor hook: bring up a fresh loop thread after a crash."""
        self._thread = self._new_thread()
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _observe(self, name: str, *args) -> None:
        m = self.metrics.get(name)
        if m is None:
            return
        if args and hasattr(m, "observe"):
            m.observe(args[0])
        elif hasattr(m, "labels") and len(args) == 2:
            m.labels(args[0]).inc(args[1])
        elif hasattr(m, "inc"):
            m.inc(*args)

    def _observe_waste(self, group, padded: int) -> None:
        """raft_batch_padding_waste_ratio: the fraction of one device
        batch's pixels that is padding — batch-fill rows plus, under
        --ragged, each row's dead embedding beyond its routed resolution
        (``pads`` are relative to the device box in both modes, so live
        pixels fall straight out of them).  Dense same-bucket batches
        report only the batch-fill share; the ragged sweep compares the
        two."""
        bh, bw = group[0].bucket[-2:]
        box = float(bh * bw)
        live = sum((bh - p[0] - p[1]) * (bw - p[2] - p[3])
                   for p in (r.pads for r in group))
        self._observe("padding_waste", (padded * box - live) / (padded * box))

    def _chunks(self, batch):
        """Split a popped run so one chunk's live-pixel footprint stays
        under ``ragged_batch_pixels`` (never splitting below one row);
        identity in dense mode or with the budget unset."""
        if not self.ragged or self.ragged_batch_pixels <= 0 \
                or len(batch) < 2:
            return [batch]
        out, cur, acc = [], [], 0
        for r in batch:
            h, w = r.rbucket
            px = h * w
            if cur and acc + px > self.ragged_batch_pixels:
                out.append(cur)
                cur, acc = [], 0
            cur.append(r)
            acc += px
        if cur:
            out.append(cur)
        return out

    def _fail_expired(self, expired) -> None:
        now = time.monotonic()
        for r in expired:
            self.timed_out += 1
            self._observe("requests", "timeout", 1)
            if r.trace is not None:
                # the whole life of an expired request WAS queue wait
                r.trace.span("queue_wait", r.enqueued_at, now,
                             status=tlm_spans.TIMEOUT)
            r.fail(DeadlineExceeded(
                f"deadline exceeded after "
                f"{time.monotonic() - r.enqueued_at:.3f}s in queue"))

    def _execute_stream(self, r) -> None:
        """One SOLO sessionful step — session opens (keyed per session:
        nothing to coalesce with) and the no-group-executor fallback.
        It observes the stream-step families at its real width (batch 1,
        occupancy 1.0); coalesced advances go through
        :meth:`_execute_stream_group` instead, which also folds into the
        shared batch-size/occupancy histograms."""
        if self.stream_fn is None:
            r.fail(RuntimeError("stream request on a batcher without a "
                                "stream executor"))
            return
        if r.abandoned:
            # the handler gave up waiting (already counted status=timeout)
            # and released the session lock: executing now would mutate
            # session state a retry may be racing — drop the step instead
            r.fail(DeadlineExceeded(
                f"stream step {r.id} abandoned by its handler"))
            return
        tr = r.trace
        if tr is not None:
            tr.span("queue_wait", r.enqueued_at, r.dequeued_at)
            tlm_spans.set_device_slot([])
        self._observe("inflight", 1)
        t0 = time.monotonic()
        err, flow, iters_used = None, None, None
        try:
            flow, iters_used = self.stream_fn(r)
        except BaseException as e:
            # the stream executor already retried cold internally; a step
            # failing here is terminal for this frame.  Never swallow a
            # shutdown signal: fail the request, then let KeyboardInterrupt
            # / SystemExit keep propagating.
            err = e
        calls = tlm_spans.take_device_slot()
        t1 = time.monotonic()
        self._observe("inflight", -1)
        self._observe("batch_latency", t1 - t0)
        self._observe("stream_steps")
        self._observe("stream_step_seconds", t1 - t0)
        self._observe("stream_step_batch", 1.0)
        self._observe("stream_step_occupancy", 1.0)
        if tr is not None:
            # spans BEFORE resolve/fail: the handler wakes on either and
            # finishes the trace — a late span would hit a closed trace
            eid = tr.span("execute", t0, t1,
                          status=(tlm_spans.OK if err is None
                                  else tlm_spans.status_of(err)),
                          batch_real=1, batch_padded=1)
            for kind, c0, c1, c2 in calls or ():
                tr.span("execute_dispatch", c0, c1, parent=eid, call=kind)
                tr.span("execute_block", c1, c2, parent=eid, call=kind)
        if err is not None:
            if self.breaker is not None:
                self.breaker.record(False)
            self._observe("requests", "error", 1)
            r.fail(err)
            if not isinstance(err, Exception):
                raise err
            return
        if self.breaker is not None:
            self.breaker.record(True)
        r.batch_real = r.batch_padded = 1
        if iters_used is not None:
            r.iters_used = int(np.asarray(iters_used).reshape(-1)[0])
            self._observe("iters_used", float(r.iters_used))
        now = time.monotonic()
        self._observe("queue_latency", r.dequeued_at - r.enqueued_at)
        self._observe("request_latency", now - r.enqueued_at)
        self._observe("requests", "ok", 1)
        self.served += 1
        if flow is None:                 # session open: no pair yet
            r.resolve(None)
        else:
            self._observe("pairs", 1.0)
            r.resolve(unpad(flow[:1], r.pads)[0])

    # -- continuous-batched stream advances --------------------------------

    def _execute_stream_group(self, batch) -> None:
        """A coalesced run of same-bucket stream ADVANCES: one batched
        device call for the whole group (serving/stream.py
        ``execute_group`` — per-row sentinel and degrade-to-cold heal
        inside), then per-row resolve/fail here.  Folds into the SAME
        batch-size/occupancy histograms as pairwise batches (a stream
        step is now a first-class device batch) and reports the
        ``raft_stream_step_*`` families at the group's real width."""
        group = []
        for r in batch:
            if r.abandoned:
                # the handler gave up waiting (already counted
                # status=timeout) and released the session lock:
                # executing now would mutate session state a retry may
                # be racing — drop the row, keep its batch-mates
                r.fail(DeadlineExceeded(
                    f"stream step {r.id} abandoned by its handler"))
                continue
            group.append(r)
        if not group:
            return
        n = len(group)
        padded = self.pad_batch_to(min(n, self.max_batch))
        traced = [r for r in group if r.trace is not None]
        t_form1 = time.monotonic()
        for r in traced:
            r.trace.span("queue_wait", r.enqueued_at, r.dequeued_at)
            r.trace.span("batch_form", r.dequeued_at, t_form1, group=n)
        self._observe_waste(group, padded)
        self._observe("inflight", 1)
        if traced:
            tlm_spans.set_device_slot([])
        t0 = time.monotonic()
        err, outcomes = None, None
        try:
            outcomes = self.stream_group_fn(group)
        except BaseException as e:
            # the group executor contains per-row failures itself; an
            # exception escaping it is a crash or a shutdown signal —
            # fail every row (fresh same-type instance each: the HTTP
            # layer stamps per-request trace ids), then let
            # KeyboardInterrupt / SystemExit keep propagating
            err = e
        calls = tlm_spans.take_device_slot() if traced else ()
        t1 = time.monotonic()
        self._observe("inflight", -1)
        self._observe("batch_latency", t1 - t0)
        self._observe("stream_step_seconds", t1 - t0)
        if err is None:
            # honest device-step accounting: only rows whose result came
            # from the batched call report its width (r.warm, set by the
            # coordinator); demoted/healed rows ran solo cold restarts
            # and report width-1 steps — raft_stream_step_batch and the
            # shared batch histograms can never claim coalescing the
            # device didn't actually do
            warm_rows = sum(1 for r in group if r.warm)
            cold_rows = n - warm_rows
            if warm_rows:
                self._observe("stream_steps")
                self._observe("stream_step_batch", float(warm_rows))
                self._observe("stream_step_occupancy", warm_rows / padded)
                self._observe("batch_size", float(warm_rows))
                self._observe("batch_occupancy", warm_rows / padded)
            if cold_rows:
                self._observe("stream_steps", cold_rows)
                for _ in range(cold_rows):
                    self._observe("stream_step_batch", 1.0)
                    self._observe("stream_step_occupancy", 1.0)
        exec_sid = tlm_spans.new_span_id()

        def _exec_span(tr, status):
            tr.span("execute", t0, t1, status=status, span_id=exec_sid,
                    batch_real=n, batch_padded=padded)
            for kind, c0, c1, c2 in calls or ():
                tr.span("execute_dispatch", c0, c1, parent=exec_sid,
                        call=kind)
                tr.span("execute_block", c1, c2, parent=exec_sid,
                        call=kind)

        if err is not None:
            if self.breaker is not None:
                self.breaker.record(False)
            for r in group:
                if r.trace is not None:
                    _exec_span(r.trace, tlm_spans.status_of(err))
                self._observe("requests", "error", 1)
                r.fail(_fresh_error(err))
            if not isinstance(err, Exception):
                raise err
            return
        now = time.monotonic()
        served = 0
        for r, (flow, iters_used, rerr) in zip(group, outcomes):
            self._observe("queue_latency", r.dequeued_at - r.enqueued_at)
            self._observe("request_latency", now - r.enqueued_at)
            r.batch_real, r.batch_padded = n, padded
            if rerr is not None:
                status = ("poisoned"
                          if getattr(rerr, "trace_status", None)
                          == tlm_spans.POISONED else "error")
                if r.trace is not None:
                    _exec_span(r.trace, tlm_spans.status_of(rerr))
                self._observe("requests", status, 1)
                r.fail(rerr)
                continue
            if r.trace is not None:
                _exec_span(r.trace, tlm_spans.OK)
            if iters_used is not None:
                r.iters_used = int(iters_used)
                self._observe("iters_used", float(r.iters_used))
            self._observe("requests", "ok", 1)
            self.served += 1
            served += 1
            r.resolve(unpad(flow[:1], r.pads)[0])
        if served:
            self._observe("pairs", float(served))

    # -- pairwise execution: retry -> bisect -> sentinel -------------------

    def _bisect_budget(self, n: int) -> int:
        """Engine-call cap for one batch's recovery: a full binary
        bisection of an all-poisoned group of n costs 2n-1 calls; allow
        that at every retry attempt, nothing more."""
        return (self.retries + 1) * 2 * n

    def _execute(self, batch) -> None:
        op = getattr(batch[0], "stream_op", None)
        if op is not None:
            if op == "advance" and self.stream_group_fn is not None:
                self._execute_stream_group(batch)
            else:
                for r in batch:
                    self._execute_stream(r)
            return
        for r in batch:
            if r.trace is not None:
                r.trace.span("queue_wait", r.enqueued_at, r.dequeued_at)
        for group in self._chunks(batch):
            n = len(group)
            padded = self.pad_batch_to(min(n, self.max_batch))
            self._observe("batch_size", float(n))
            self._observe("batch_occupancy", n / padded)
            self._observe_waste(group, padded)
            self._observe("inflight", 1)
            t0 = time.monotonic()
            try:
                budget = [self._bisect_budget(n)]
                self._run_group(group, budget)
            finally:
                self._observe("inflight", -1)
                self._observe("batch_latency", time.monotonic() - t0)

    def _run_group(self, group, budget, formed: bool = False) -> None:
        """Run one same-bucket group; on persistent engine failure, split
        and retry halves so only the guilty request(s) fail.  ``budget``
        is the batch-wide engine-call allowance (mutable 1-list);
        ``formed`` marks bisection sub-groups (the batch_form span is
        recorded once, on the original group)."""
        n = len(group)
        padded = self.pad_batch_to(min(n, self.max_batch))
        traced = [r for r in group if r.trace is not None]
        t_form1 = time.monotonic()
        if not formed:
            for r in traced:
                r.trace.span("batch_form", r.dequeued_at, t_form1, group=n)
        im1 = np.concatenate([r.image1 for r in group]
                             + [group[-1].image1] * (padded - n))
        im2 = np.concatenate([r.image2 for r in group]
                             + [group[-1].image2] * (padded - n))
        t_pad1 = time.monotonic()
        for r in traced:
            r.trace.span("pad", t_form1, t_pad1, padded=padded)
        out, err, attempts = None, None, 0
        t_exec0 = time.monotonic()
        if traced:
            tlm_spans.set_device_slot([])
        while attempts <= self.retries and budget[0] > 0:
            attempts += 1
            budget[0] -= 1
            try:
                if self.ragged:
                    # per-row live sizes from each request's routed
                    # bucket; filler rows repeat the last request's, to
                    # match its repeated pixels
                    rb = ([r.rbucket for r in group]
                          + [group[-1].rbucket] * (padded - n))
                    out = self.run_fn(group[0].bucket, im1, im2,
                                      np.asarray(rb, np.int32))
                else:
                    out = self.run_fn(group[0].bucket, im1, im2)
            except Exception as e:
                # transient device errors heal under a short backoff;
                # persistent ones fall through to bisection below
                if self.breaker is not None:
                    self.breaker.record(False)
                err = e
                if attempts <= self.retries and budget[0] > 0:
                    time.sleep(self.retry_backoff_s)
                continue
            except BaseException as e:
                # shutdown (KeyboardInterrupt/SystemExit): fail the group
                # so no handler hangs, then keep propagating — swallowing
                # it here would eat Ctrl-C.  Same type per waiter, but a
                # FRESH instance each (_fresh_error)
                t_x = time.monotonic()
                tlm_spans.take_device_slot()
                sid = tlm_spans.new_span_id()
                for r in group:
                    if r.trace is not None:
                        r.trace.span("execute", t_exec0, t_x,
                                     status=tlm_spans.ERROR, span_id=sid,
                                     batch_real=n, batch_padded=padded)
                    self._observe("requests", "error", 1)
                    r.fail(_fresh_error(e))
                raise
            if self.breaker is not None:
                self.breaker.record(True)
            err = None
            break
        calls = tlm_spans.take_device_slot() if traced else ()
        t_exec1 = time.monotonic()
        # co-batched requests SHARE one execute span id (the join key
        # across their traces); each trace holds its own copy with its
        # own queue spans around it
        exec_sid = tlm_spans.new_span_id()

        def _exec_span(tr, status):
            tr.span("execute", t_exec0, t_exec1, status=status,
                    span_id=exec_sid, batch_real=n, batch_padded=padded,
                    attempts=attempts)
            for kind, c0, c1, c2 in calls or ():
                tr.span("execute_dispatch", c0, c1, parent=exec_sid,
                        call=kind)
                tr.span("execute_block", c1, c2, parent=exec_sid,
                        call=kind)

        if out is None and err is None:
            # budget ran dry before this sub-group got a single attempt
            err = RuntimeError("bisection budget exhausted before this "
                               "sub-group could execute")
        if err is not None:
            if n == 1 and attempts:
                # bisected down to the guilty request: the 'poisoned'
                # error class — co-batched neighbors already succeeded
                if group[0].trace is not None:
                    _exec_span(group[0].trace, tlm_spans.POISONED)
                self._observe("requests", "poisoned", 1)
                group[0].fail(PoisonedRequest(
                    f"request {group[0].id} poisons its batch: engine "
                    f"failed after {attempts} attempt(s): {err}"))
                return
            if budget[0] <= 0:
                # retry budget exhausted mid-bisection: the engine is
                # sick, not one request — fail the remainder as plain
                # errors (the breaker is already counting these).  Each
                # request gets its OWN exception instance: the HTTP
                # layer stamps the request's trace id onto it, and a
                # shared instance would cross-wire ids between
                # co-batched clients
                for r in group:
                    if r.trace is not None:
                        _exec_span(r.trace, tlm_spans.ERROR)
                    self._observe("requests", "error", 1)
                    r.fail(RuntimeError(
                        f"engine failing across requests (retry budget "
                        f"exhausted): {err}"))
                return
            # the failed attempt stays visible in every trace (status
            # "retry"); the sub-groups record their own execute spans
            for r in traced:
                _exec_span(r.trace, "retry")
            mid = n // 2
            self._run_group(group[:mid], budget, formed=True)
            self._run_group(group[mid:], budget, formed=True)
            return
        # converge-policy engines return (flows, per-row iters_used); only
        # REAL rows are accounted — padding rows repeat the last request
        # and would skew the raft_iters_used distribution
        iters_used = None
        flows = out
        if isinstance(flows, tuple):
            flows, iters_used = flows
        flows = np.asarray(flows)
        # non-finite OUTPUT sentinel: inputs were validated at the HTTP
        # edge, so a NaN/Inf row here is the engine's failure — fail that
        # row alone, its neighbors are fine (per-sample independence)
        row_ok = np.isfinite(flows[:n].reshape(n, -1)).all(axis=1)
        now = time.monotonic()
        served = 0
        for i, r in enumerate(group):
            r.batch_real, r.batch_padded = n, padded
            if iters_used is not None:
                r.iters_used = int(iters_used[i])
                self._observe("iters_used", float(iters_used[i]))
            self._observe("queue_latency", r.dequeued_at - r.enqueued_at)
            self._observe("request_latency", now - r.enqueued_at)
            if row_ok[i]:
                if r.trace is not None:
                    _exec_span(r.trace, tlm_spans.OK)
                self._observe("requests", "ok", 1)
                self.served += 1
                served += 1
                r.resolve(unpad(flows[i:i + 1], r.pads)[0])
            else:
                if r.trace is not None:
                    _exec_span(r.trace, tlm_spans.POISONED)
                self._observe("nonfinite")
                self._observe("requests", "poisoned", 1)
                log = tlm_events.current()
                if log is not None:
                    # joinable to the request trace (chaos drills): the
                    # sentinel's run-log record carries the trace id
                    log.event("nonfinite_output", request=r.id,
                              trace_id=(r.trace.trace_id
                                        if r.trace is not None else None))
                r.fail(NonFiniteOutput(
                    f"non-finite flow output for request {r.id} "
                    f"(poisoned row in an otherwise-healthy batch)"))
        if served:
            self._observe("pairs", float(served))

    # -- the loop + its crash surface --------------------------------------

    def _loop(self) -> None:
        while True:
            batch, expired = self.queue.take_batch(self.max_batch,
                                                   self.max_wait)
            self._fail_expired(expired)
            if batch is None:        # queue closed and empty: drained
                return
            if batch:
                self.batches += 1
                # cleared only on the success path: an exception escaping
                # here must leave the batch visible to _thread_main's
                # crash handler (it fails whatever is not yet done)
                self._inflight_batch = batch
                # ambient trace ids for this batch: out-of-band
                # diagnostics fired from under here (fault_injected,
                # lock_violation, the non-finite sentinel) become
                # joinable to the request traces they hit
                tlm_spans.set_current_trace_ids(tuple(
                    r.trace.trace_id for r in batch
                    if r.trace is not None))
                try:
                    if self.faults is not None:
                        self.faults.maybe_kill()   # chaos: thread-death arm
                    self._execute(batch)
                finally:
                    tlm_spans.set_current_trace_ids(())
                self._inflight_batch = None

    def _thread_main(self) -> None:
        try:
            self._loop()
        except BaseException as e:
            # the crash surface: fail whatever was popped but unresolved
            # (handler threads must never hang on a dead batcher), then
            # hand an Exception to the supervisor for restart; shutdown
            # signals propagate — threading's excepthook reports them
            for r in (self._inflight_batch or []):
                if not r.done:
                    self._observe("requests", "error", 1)
                    if r.trace is not None:
                        # finish (idempotent) BEFORE failing: the
                        # supervisor dumps the flight recorder on this
                        # thread right after, and the crashed trace must
                        # already be in the ring — the woken handler's
                        # own finish becomes a no-op
                        r.trace.finish(tlm_spans.ERROR)
                    r.fail(BatcherCrashed(
                        f"batcher thread died mid-batch ({e!r}); "
                        f"the supervisor restarts it — retry"))
            self._inflight_batch = None
            if self.on_crash is not None and isinstance(e, Exception):
                self.on_crash(e)
            else:
                raise
