"""Dynamic micro-batcher: coalesce, pad, execute, deliver.

One daemon thread pulls same-bucket FIFO runs from the admission queue
(``RequestQueue.take_batch``: full batch, aged ``max_wait_ms``, or drain —
whichever first), pads the group up to the next declared batch step by
repeating the last pair (any filler works — per-sample inference is
independent; repetition keeps values finite for the instance norms), runs
the warm engine, slices real rows back out, unpads each to its request's
original resolution, and resolves the waiting handler threads.

The engine is injected as a callable ``run(bucket, im1, im2) -> flow`` so
tests can drive the batching policy with a stub (slow / counting / failing)
engine and never touch a compile.

Streaming steps (serving/stream.py) share this thread — ONE owner of the
device — but execute per session via the injected ``stream_fn``: the
queue keys them per session id, so a popped run is either all-pairwise
(coalesced) or a single session's step, never a mix.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..data.pipeline import unpad
from .queue import DeadlineExceeded, RequestQueue


class MicroBatcher:
    def __init__(self, queue: RequestQueue, run_fn: Callable,
                 pad_batch_to: Callable[[int], int], max_batch: int,
                 max_wait_ms: float, metrics: Optional[Dict] = None,
                 stream_fn: Optional[Callable] = None):
        self.queue = queue
        self.run_fn = run_fn
        # streaming steps (serving/stream.py) ride the same queue and the
        # same device-owning thread but execute per session: stream_fn
        # takes ONE StreamRequest and returns (padded flow or None,
        # iters_used or None)
        self.stream_fn = stream_fn
        self.pad_batch_to = pad_batch_to
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        self.metrics = metrics or {}
        self.batches = 0
        self.served = 0
        self.timed_out = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="raft-serving-batcher")

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _observe(self, name: str, *args) -> None:
        m = self.metrics.get(name)
        if m is None:
            return
        if args and hasattr(m, "observe"):
            m.observe(args[0])
        elif hasattr(m, "labels") and len(args) == 2:
            m.labels(args[0]).inc(args[1])
        elif hasattr(m, "inc"):
            m.inc(*args)

    def _fail_expired(self, expired) -> None:
        for r in expired:
            self.timed_out += 1
            self._observe("requests", "timeout", 1)
            r.fail(DeadlineExceeded(
                f"deadline exceeded after "
                f"{time.monotonic() - r.enqueued_at:.3f}s in queue"))

    def _execute_stream(self, r) -> None:
        """One sessionful step (never coalesced: the queue keys stream
        requests per session).  Batch-size/occupancy histograms are left
        to pairwise batches — a stream step is definitionally batch 1 and
        would only dilute the coalescing signal they exist to expose."""
        if self.stream_fn is None:
            r.fail(RuntimeError("stream request on a batcher without a "
                                "stream executor"))
            return
        if r.abandoned:
            # the handler gave up waiting (already counted status=timeout)
            # and released the session lock: executing now would mutate
            # session state a retry may be racing — drop the step instead
            r.fail(DeadlineExceeded(
                f"stream step {r.id} abandoned by its handler"))
            return
        self._observe("inflight", 1)
        t0 = time.monotonic()
        try:
            flow, iters_used = self.stream_fn(r)
        except BaseException as e:
            self._observe("requests", "error", 1)
            r.fail(e)
            return
        finally:
            self._observe("inflight", -1)
            self._observe("batch_latency", time.monotonic() - t0)
        r.batch_real = r.batch_padded = 1
        if iters_used is not None:
            r.iters_used = int(np.asarray(iters_used).reshape(-1)[0])
            self._observe("iters_used", float(r.iters_used))
        now = time.monotonic()
        self._observe("queue_latency", r.dequeued_at - r.enqueued_at)
        self._observe("request_latency", now - r.enqueued_at)
        self._observe("requests", "ok", 1)
        self.served += 1
        if flow is None:                 # session open: no pair yet
            r.resolve(None)
        else:
            self._observe("pairs", 1.0)
            r.resolve(unpad(flow[:1], r.pads)[0])

    def _execute(self, batch) -> None:
        if getattr(batch[0], "stream_op", None) is not None:
            for r in batch:
                self._execute_stream(r)
            return
        n = len(batch)
        padded = self.pad_batch_to(min(n, self.max_batch))
        im1 = np.concatenate([r.image1 for r in batch]
                             + [batch[-1].image1] * (padded - n))
        im2 = np.concatenate([r.image2 for r in batch]
                             + [batch[-1].image2] * (padded - n))
        self._observe("batch_size", float(n))
        self._observe("batch_occupancy", n / padded)
        self._observe("inflight", 1)
        t0 = time.monotonic()
        try:
            flows = self.run_fn(batch[0].bucket, im1, im2)
        except BaseException as e:
            for r in batch:
                self._observe("requests", "error", 1)
                r.fail(e)
            return
        finally:
            self._observe("inflight", -1)
            self._observe("batch_latency", time.monotonic() - t0)
        # converge-policy engines return (flows, per-row iters_used); only
        # REAL rows are accounted — padding rows repeat the last request
        # and would skew the raft_iters_used distribution
        iters_used = None
        if isinstance(flows, tuple):
            flows, iters_used = flows
        now = time.monotonic()
        for i, r in enumerate(batch):
            r.batch_real, r.batch_padded = n, padded
            if iters_used is not None:
                r.iters_used = int(iters_used[i])
                self._observe("iters_used", float(iters_used[i]))
            self._observe("queue_latency", r.dequeued_at - r.enqueued_at)
            self._observe("request_latency", now - r.enqueued_at)
            self._observe("requests", "ok", 1)
            self.served += 1
            r.resolve(unpad(flows[i:i + 1], r.pads)[0])
        self._observe("pairs", float(n))

    def _loop(self) -> None:
        while True:
            batch, expired = self.queue.take_batch(self.max_batch,
                                                   self.max_wait)
            self._fail_expired(expired)
            if batch is None:        # queue closed and empty: drained
                return
            if batch:
                self.batches += 1
                self._execute(batch)
