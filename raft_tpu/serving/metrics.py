"""Serving metric set + compat shim over the shared telemetry registry.

The Counter / Gauge / Histogram / Registry primitives were born here and
now live in :mod:`raft_tpu.telemetry.registry`, where the training loop,
``bench.py`` and the data loaders count with the same classes (one
observability spine — OBSERVABILITY.md).  This module re-exports them
unchanged (``from raft_tpu.serving.metrics import Counter`` keeps working
and *is* the telemetry class) and keeps the serving-specific part: the
metric set whose names SERVING.md, the tests and the Prometheus scrape
contract pin.  The ``/metrics`` text output is byte-identical to the
pre-refactor renderer.
"""

from __future__ import annotations

import functools
from typing import Dict

from ..telemetry.registry import (_Metric,  # noqa: F401 — compat re-export
                                  Counter, Gauge, Histogram, Registry,
                                  DEFAULT_LATENCY_BUCKETS,
                                  ITERS_USED_BUCKETS, _fmt,
                                  register_process_start_time)


def make_serving_metrics(registry: Registry, config,
                         queue_depth_fn=None) -> Dict[str, _Metric]:
    """The serving stack's metric set, in one place so the names in
    SERVING.md, the tests, and the code can't drift.  ``queue_depth_fn``
    makes the depth gauge a live callback (sampled at scrape time) so it
    can never go stale between submissions."""
    occ = tuple(i / 10 for i in range(1, 11))
    batch = tuple(float(s) for s in config.batch_steps)
    register_process_start_time(registry)
    return {
        "requests": registry.counter(
            "raft_serving_requests_total",
            "Requests by terminal status",
            labelnames=("status",)),
        "pairs": registry.counter(
            "raft_serving_pairs_total",
            "Image pairs successfully served"),
        "queue_depth": registry.gauge(
            "raft_serving_queue_depth",
            "Requests currently waiting in the admission queue",
            fn=queue_depth_fn),
        "inflight": registry.gauge(
            "raft_serving_inflight_batches",
            "Device batches currently executing"),
        "batch_size": registry.histogram(
            "raft_serving_batch_size",
            "Real (unpadded) requests per device batch",
            buckets=batch),
        "batch_occupancy": registry.histogram(
            "raft_serving_batch_occupancy",
            "Real requests / padded batch size per device call",
            buckets=occ),
        "padding_waste": registry.histogram(
            "raft_batch_padding_waste_ratio",
            "Padding pixels / total pixels per device batch: batch-fill "
            "rows plus, under --ragged, each row's dead embedding beyond "
            "its routed resolution (observed on pairwise and coalesced "
            "stream batches alike)",
            buckets=occ),
        "request_latency": registry.histogram(
            "raft_serving_request_latency_seconds",
            "End-to-end request latency (enqueue to result)"),
        "queue_latency": registry.histogram(
            "raft_serving_queue_latency_seconds",
            "Time spent waiting for a batch slot"),
        "batch_latency": registry.histogram(
            "raft_serving_batch_latency_seconds",
            "Device execution time per batch"),
        "compile_hits": registry.counter(
            "raft_serving_compile_cache_hits_total",
            "Device calls served by a warm executable"),
        "compile_misses": registry.counter(
            "raft_serving_compile_cache_misses_total",
            "Device calls that had to compile (0 after warmup = the "
            "no-recompile-storm guarantee)"),
        "iters_used": (iters_used := registry.histogram(
            "raft_iters_used",
            "GRU iterations spent per request — fills only under "
            "--iters-policy converge:* (per-sample early exit); stays "
            "empty under 'fixed', where every request costs the declared "
            "count",
            buckets=ITERS_USED_BUCKETS)),
        # live mean over everything observed so far: sum/count of the
        # histogram, sampled at scrape time — never goes stale
        "iters_mean": registry.gauge(
            "raft_iters_mean",
            "Mean GRU iterations per request (adaptive-compute saving)",
            fn=iters_used.mean),
    }


def make_stream_metrics(registry: Registry, store,
                        buckets=None) -> Dict[str, _Metric]:
    """The streaming (/v1/stream) metric families — one definition site,
    same contract as :func:`make_serving_metrics`.  The session gauges are
    live callbacks on the store; the eviction counter is handed back to
    the store so it can label the reason at the decision site.
    ``buckets`` (the declared resolution buckets) wires the per-bucket
    slot-pool gauges — slots in use vs capacity, the device-memory
    utilization of the continuous-batching stream path."""
    m = {
        "sessions_active": registry.gauge(
            "raft_stream_sessions_active",
            "Sessions holding device-resident feature maps "
            "(bounded by --max-sessions)",
            fn=store.active_count),
        "sessions_resident": registry.gauge(
            "raft_stream_sessions_resident",
            "Session records resident, demoted (features evicted) included",
            fn=store.resident_count),
        "opens": registry.counter(
            "raft_stream_opens_total",
            "Sessions opened"),
        "frames": registry.counter(
            "raft_stream_frames_total",
            "Stream advances served (one flow pair each)"),
        "fnet_hits": registry.counter(
            "raft_stream_fnet_cache_hits_total",
            "Advances served from cached previous-frame features "
            "(ONE encoder pass instead of two)"),
        "fnet_misses": registry.counter(
            "raft_stream_fnet_cache_misses_total",
            "Advances that cold-restarted (features evicted: two encoder "
            "passes, pairwise cost, correct flow)"),
        "evictions": registry.counter(
            "raft_stream_evictions_total",
            "Session evictions by reason: lru (features demoted past "
            "--max-sessions), ttl (idle record reaped), capacity "
            "(record evicted outright), degraded (breaker open / faulted "
            "step: features dropped, next advance cold-restarts)",
            labelnames=("reason",)),
        "degraded": registry.counter(
            "raft_stream_degraded_total",
            "Stream advances whose warm step faulted (engine error or "
            "non-finite output) and were transparently retried through "
            "the cold-restart path"),
        # the continuous-batching observables (ROADMAP item 1): stream
        # device steps now coalesce across sessions, so these report the
        # REAL per-step width (batched advances also fold into the
        # shared raft_serving_batch_size/occupancy histograms)
        "steps": registry.counter(
            "raft_stream_steps_total",
            "Stream device steps executed (one per device call: a "
            "coalesced multi-session advance counts once)"),
        "step_seconds": registry.histogram(
            "raft_stream_step_seconds",
            "Device time per stream step (one batched step advances "
            "every coalesced session)"),
        "step_batch": registry.histogram(
            "raft_stream_step_batch",
            "Sessions coalesced per stream device step (continuous "
            "batching width; 1 = a solo step / session open)",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)),
        "step_occupancy": registry.histogram(
            "raft_stream_step_occupancy",
            "Real sessions / padded slots per stream device step (the "
            "stream twin of raft_serving_batch_occupancy)",
            buckets=tuple(i / 10 for i in range(1, 11))),
    }
    store.evictions = m["evictions"]
    if buckets:
        pool = store.pool
        in_use = registry.gauge(
            "raft_stream_slots_in_use",
            "Device-resident slot-pool rows allocated per bucket "
            "(sessions whose maps sit in batch slots, ready to coalesce)",
            labelnames=("bucket",))
        cap = registry.gauge(
            "raft_stream_slot_capacity",
            "Slot-pool rows declared per bucket (--max-sessions)",
            labelnames=("bucket",))
        for (h, w) in buckets:
            in_use.labels(f"{h}x{w}").set_fn(
                functools.partial(pool.in_use, (h, w)))
            cap.labels(f"{h}x{w}").set(pool.capacity)
        m["slots_in_use"], m["slot_capacity"] = in_use, cap
        if getattr(pool, "arena", None) is not None:
            # ragged arena (SERVING.md "Ragged serving"): the buckets all
            # map onto one max-box arena, so per-bucket in_use gauges
            # report the shared count; this gauge prices how much of the
            # allocated arena rows is LIVE page pixels (vs dead embedding)
            m["arena_live_pixels"] = registry.gauge(
                "raft_stream_arena_live_pixels",
                "Live page pixels resident in the shared ragged slot "
                "arena (sum of slot extents; the box-pixel denominator "
                "is slots_in_use x arena h x w)",
                fn=functools.partial(pool.used_pixels, pool.arena))
    return m


def make_slo_metrics(registry: Registry, slo) -> Dict[str, _Metric]:
    """SLO burn-rate families over the span data (telemetry/spans.py
    SLOTracker).  Registered only while tracing is on (trace_sample > 0)
    so `--trace-sample 0` keeps the /metrics exposition free of tracing
    families.  The violation counter is handed back to the tracker (the
    decision-site labeling pattern the session store uses)."""
    burn = registry.gauge(
        "raft_slo_burn_rate",
        "Error-budget burn rate per request class: violating fraction of "
        "the SLO window / slo_budget (1 = burning exactly the budget, "
        ">> 1 = this replica cannot meet its latency objective)",
        labelnames=("class",))
    for cls in sorted(slo.objectives):
        burn.labels(cls).set_fn(functools.partial(slo.burn_rate, cls))
    violations = registry.counter(
        "raft_slo_violations_total",
        "Requests that burned error budget, by class (slower than the "
        "class objective, or terminated shed/timeout/poisoned/error)",
        labelnames=("class",))
    for cls in sorted(slo.objectives):
        violations.labels(cls)        # pre-create: exposition shows 0
    slo.violations = violations
    return {"burn_rate": burn, "violations": violations}


def make_robustness_metrics(registry: Registry,
                            breaker=None) -> Dict[str, _Metric]:
    """The self-healing metric families (failure containment, ISSUE 11):
    always registered — they are production health signals, not debug
    toggles.  The breaker's transition counter is handed back to it (the
    decision-site labeling pattern the session store uses)."""
    m = {
        "nonfinite": registry.counter(
            "raft_nonfinite_outputs_total",
            "Flow output rows rejected by the non-finite sentinel "
            "(each fails only its own request with a 500)"),
        "batcher_restarts": registry.counter(
            "raft_batcher_restarts_total",
            "Batcher-thread crashes recovered by the supervisor "
            "(healthz reports degraded while recent)"),
    }
    if breaker is not None:
        registry.gauge(
            "raft_breaker_state",
            "Circuit breaker state: 0 closed, 1 half-open, 2 open "
            "(open sheds with 503 + Retry-After)",
            fn=breaker.state_code)
        m["breaker_transitions"] = registry.counter(
            "raft_breaker_transitions_total",
            "Breaker state transitions by destination",
            labelnames=("to",))
        breaker.transitions = m["breaker_transitions"]
    return m


def make_engine_cache_metrics(registry: Registry) -> Dict[str, _Metric]:
    """AOT executable-cache families (serving/aot_cache.py) — registered
    only when --engine-cache-dir attaches a cache, so a cacheless server's
    /metrics exposition is untouched.  The counters are bulk-filled from
    the cache's warmup stats after start() and incremented on later
    export/prestage activity; the histogram prices deserialize time (the
    thing that replaced a multi-second XLA compile)."""
    return {
        "hits": registry.counter(
            "raft_engine_cache_hits_total",
            "Warmup keys served from the serialized AOT cache "
            "(deserialized executable — no XLA compile)"),
        "misses": registry.counter(
            "raft_engine_cache_misses_total",
            "Warmup keys that fell back to compiling (absent, corrupt, "
            "or stale cache directory)"),
        "loads": registry.counter(
            "raft_engine_cache_loads_total",
            "Serialized-executable deserialize attempts"),
        "load_seconds": registry.histogram(
            "raft_engine_cache_load_seconds",
            "Deserialize time per cached executable (the cold-start cost "
            "that replaced an XLA compile)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)),
    }


def make_fault_metrics(registry: Registry) -> Dict[str, _Metric]:
    """Registered only when --chaos/RAFT_TPU_CHAOS arms the injector, so
    an un-drilled server's /metrics exposition carries no chaos families."""
    return {
        "faults": registry.counter(
            "raft_fault_injected_total",
            "Faults injected by the chaos harness, by arm "
            "(serving/faults.py; absent unless chaos is armed)",
            labelnames=("arm",)),
    }
