"""Sessionful streaming flow: the machinery behind ``POST /v1/stream``.

Protocol (wire format parsed in http.py): a client *opens* a session with
its first frame, *advances* it one frame at a time — each advance returns
flow(prev -> cur) — and *closes* it.  Per advance the server runs ONE
encoder pass (the current frame's; the previous frame's fmap/context maps
are cached device-side in the session) and warm-starts the recurrence
from the previous flow forward-projected along itself
(ops/warmstart.warm_start_seed — RAFT's own Sintel video protocol), so a
``converge:eps`` iteration policy exits in a fraction of the cold count.

Stream steps ride the SAME admission queue and batcher thread as
``/v1/flow`` (bounded depth -> 429, deadlines -> 504, graceful drain),
keyed per session so they never coalesce with pairwise batches; the
session lock serializes frames within a session (a concurrent advance on
the same session answers 409 rather than reordering the recurrence).

Thread model (SERVING.md "Threading model"): the handler thread holds
``Session.lock`` across the WHOLE advance — including ``queue.submit``
(which takes the queue lock) and the blocking wait — which is why the
declared hierarchy orders ``Session.lock`` OUTSIDE
``RequestQueue._lock``.  The coordinator itself holds no lock: session
state is mutated only in :meth:`execute` on the batcher thread, while
the handler's session lock keeps any second frame of the same session
out; ``store._evict`` (a thread-safe counter inc) is the only store
touch made without the store lock.

Evicted (demoted) sessions degrade transparently: the advance re-encodes
the retained previous frame — the cold two-encoder cost, the same flow.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..data.pipeline import pad_to_shape
from ..ops.warmstart import warm_start_seed
from ..telemetry import events as tlm_events
from ..telemetry import spans as tlm_spans
from .batcher import NonFiniteOutput
from .queue import (DeadlineExceeded, Draining, RejectedError, Request,
                    RequestQueue)
from .session import Session, SessionStore


class UnknownSession(RejectedError):
    """Session id never existed, was closed, or aged out (TTL) — reopen."""
    http_status = 404


class SessionBusy(RejectedError):
    """A frame for this session is already in flight (advances are
    strictly sequential: frame t's flow seeds frame t+1)."""
    http_status = 409


class StreamRequest(Request):
    """One stream step in flight.  ``bucket`` is the queue key — per
    session, so the batcher pops stream steps alone, never coalesced with
    pairwise work or other sessions."""

    __slots__ = ("session", "stream_op", "warm", "frame", "abandoned")

    def __init__(self, session: Session, op: str, image_padded, pads,
                 deadline: float):
        super().__init__(image_padded, None, ("stream", session.id), pads,
                         deadline)
        self.session = session
        self.stream_op = op              # "open" | "advance"
        self.warm = False                # set at execute time
        self.frame = 0
        # set by the handler when wait() gives up (batcher stalled past
        # the deadline margin): the batcher must then SKIP the step
        # instead of mutating session state after the session lock was
        # released — a late orphaned step would otherwise consume the
        # frame a client retry is about to resubmit
        self.abandoned = False


class StreamCoordinator:
    """Owns the session store and the stream-step device recipe.

    Handler threads call :meth:`open`/:meth:`advance`/:meth:`close`
    (validate, lock the session, enqueue, block); the batcher thread calls
    :meth:`execute` (the only place device state moves).
    """

    def __init__(self, store: SessionStore, sconfig, queue: RequestQueue,
                 metrics: Dict, count_fn, faults=None, nonfinite=None,
                 breaker=None, tracer=None):
        self.store = store
        self.sconfig = sconfig
        self.queue = queue
        self.metrics = metrics           # make_stream_metrics families
        self.count = count_fn            # FlowServer.count_request
        self.faults = faults             # chaos injector (session arm)
        self.nonfinite = nonfinite       # raft_nonfinite_outputs_total
        self.breaker = breaker           # CircuitBreaker or None
        self.tracer = tracer             # telemetry.spans.Tracer or None

    # -- handler-thread API ------------------------------------------------

    def open(self, image: np.ndarray, deadline_ms: Optional[float],
             trace_id: Optional[str] = None,
             finish_trace: bool = True) -> Dict:
        from .http import BadRequest    # circular-free: http imports us not
        self.store.sweep()
        h, w = image.shape[0], image.shape[1]
        bucket = self.sconfig.route(h, w)
        if bucket is None:
            raise BadRequest(
                f"no declared bucket fits ({h}, {w}); buckets: "
                f"{[f'{bh}x{bw}' for bh, bw in self.sconfig.buckets]}")
        s = self.store.open(bucket)
        try:
            with s.lock:
                req = self._run_step(s, "open", image, deadline_ms,
                                     trace_id=trace_id,
                                     finish_trace=finish_trace)
        except BaseException:
            # no half-open sessions — but close AFTER releasing s.lock:
            # store.close takes the store lock, which the hierarchy orders
            # OUTSIDE the session lock (the id never reached the client,
            # so nothing can race the record between release and close)
            self.store.close(s.id)
            raise
        self.metrics["opens"].inc()
        res = {"session": s.id, "frame": 0,
               "meta": {"bucket": list(bucket)}, "_trace": req.trace,
               "_finished_at": req.finished_at}
        if req.trace is not None:
            res["meta"]["trace_id"] = req.trace.trace_id
        return res

    def advance(self, sid: Optional[str], image: np.ndarray,
                deadline_ms: Optional[float],
                trace_id: Optional[str] = None,
                finish_trace: bool = True) -> Dict:
        from .http import BadRequest
        self.store.sweep()
        s = self.store.get(sid) if sid else None
        if s is None:
            self.count("unknown_session")
            raise UnknownSession(
                f"unknown session {sid!r} (closed, expired after "
                f"{self.sconfig.session_ttl_s:.0f}s idle, or never "
                f"opened) — open a new one")
        if not s.lock.acquire(blocking=False):
            self.count("session_busy")
            raise SessionBusy(f"session {sid} already has a frame in "
                              f"flight; advances are sequential")
        try:
            h, w = image.shape[0], image.shape[1]
            if self.sconfig.route(h, w) != s.bucket:
                raise BadRequest(
                    f"frame ({h}, {w}) does not route to this session's "
                    f"bucket {s.bucket}; resolution changes mid-stream "
                    f"need a new session")
            req = self._run_step(s, "advance", image, deadline_ms,
                                 trace_id=trace_id,
                                 finish_trace=finish_trace)
        finally:
            s.lock.release()
        meta = {"bucket": list(s.bucket), "warm": req.warm,
                "batch_real": req.batch_real,
                "batch_padded": req.batch_padded}
        if req.iters_used is not None:
            meta["iters_used"] = req.iters_used
        if req.trace is not None:
            meta["trace_id"] = req.trace.trace_id
        return {"session": s.id, "frame": req.frame, "flow": req.result,
                "meta": meta, "_trace": req.trace,
                "_finished_at": req.finished_at}

    def close(self, sid: Optional[str]) -> Dict:
        s = self.store.close(sid) if sid else None
        if s is None:
            self.count("unknown_session")
            raise UnknownSession(f"unknown session {sid!r}")
        return {"session": sid, "closed": True, "frames": s.frames}

    def _run_step(self, s: Session, op: str, image: np.ndarray,
                  deadline_ms: Optional[float],
                  trace_id: Optional[str] = None,
                  finish_trace: bool = True) -> StreamRequest:
        """Pad, enqueue, block until the batcher resolves — the stream
        twin of FlowServer.infer, same deadline/shed/drain accounting and
        the same trace lifecycle: the trace closes HERE on every failure
        path (status from the exception); on success the HTTP handler
        finishes it after the respond span (``finish_trace=False``), or
        this method does for direct callers."""
        from .http import BadRequest
        tr = (self.tracer.start("stream", trace_id)
              if self.tracer is not None else None)
        t0 = time.monotonic()
        try:
            dl = (self.sconfig.default_deadline_ms if deadline_ms is None
                  else min(deadline_ms, self.sconfig.default_deadline_ms))
            if dl <= 0:
                raise BadRequest(f"deadline_ms must be positive, got {dl}")
            imp, pads = pad_to_shape(image[None].astype(np.float32),
                                     s.bucket)
            req = StreamRequest(s, op, imp, pads,
                                deadline=time.monotonic() + dl / 1000.0)
            req.trace = tr
            if tr is not None:
                tr.span("admit", t0, time.monotonic(), op=op,
                        session=s.id)
            try:
                self.queue.submit(req)
            except Draining:
                self.count("draining")
                raise
            except Exception:           # QueueFull: overload shed, HTTP 429
                self.count("shed")
                raise
            try:
                req.wait(timeout=dl / 1000.0 + max(30.0, dl / 1000.0))
            except DeadlineExceeded:
                # the step may still be queued (or mid-execution on a
                # stalled device): mark it so the batcher drops it instead
                # of advancing the session after this thread releases its
                # lock
                req.abandoned = True
                if req.error is None:
                    self.count("timeout")
                raise
        except BaseException as e:
            if tr is not None:
                # stamp-if-absent (see FlowServer.infer): never overwrite
                # another request's id on a shared exception instance
                if getattr(e, "trace_id", None) is None:
                    e.trace_id = tr.trace_id
                tr.finish(tlm_spans.status_of(e))
            raise
        if finish_trace and tr is not None:
            tr.finish()
        return req

    # -- batcher-thread API ------------------------------------------------

    def execute(self, req: StreamRequest, engine):
        """Run one stream step on the device.  Returns (padded flow or
        None, iters_used or None); all session/cache mutation happens
        here, on the single thread that owns the device.

        Degradation ladder (SERVING.md): a *warm* step that faults —
        engine exception or a non-finite flow output (e.g. poisoned
        cached maps) — drops the session's device features and retries
        once through the SAME transparent cold-restart path an evicted
        session already takes: two encoder passes, correct flow, no
        error.  A cold step that faults is terminal for this frame (the
        client retries; session state was not advanced)."""
        s = req.session
        if req.stream_op == "open":
            fmap, cnet = engine.run_encode(s.bucket, req.image1)
            self.store.attach_features(s, fmap, cnet, None)
            s.last_image = req.image1
            return None, None
        if self.faults is not None:
            self.faults.corrupt_session(s)   # chaos: session-map arm
        warm = s.has_features
        try:
            flow, iters_used = self._advance_once(s, req, engine, warm)
        except Exception:
            # the failed warm call still counts against the breaker even
            # though the advance will heal: it measures engine-call
            # health, and a 100%-warm-failure mode must be visible (the
            # batcher records only the advance's terminal outcome)
            if self.breaker is not None:
                self.breaker.record(False)
            if not warm:
                raise
            s.drop_features()
            self.store._evict("degraded")
            self.metrics["degraded"].inc()
            if req.trace is not None:
                # the client gets a 200 but the trace says what it cost:
                # degraded outranks ok and is always recorder-retained
                req.trace.set_status(tlm_spans.DEGRADED)
            flow, iters_used = self._advance_once(s, req, engine,
                                                  warm=False)
            warm = False
        s.frames += 1
        req.warm = warm
        req.frame = s.frames
        self.metrics["frames"].inc()
        return flow, iters_used

    def _advance_once(self, s: Session, req: StreamRequest, engine,
                      warm: bool):
        """One advance attempt.  Session state (maps, last_image) is
        mutated only AFTER the output passes the non-finite sentinel, so
        a faulted attempt leaves the session exactly where it was."""
        H, W = s.bucket
        if warm:
            # ONE encoder pass this step: frame t's maps are resident
            fmap_p, cnet_p = s.fmap, s.cnet
            init = warm_start_seed(s.prev_flow_lr, (H // 8, W // 8))
            self.metrics["fnet_hits"].inc()
        else:
            # demoted/degraded: cold two-encoder restart from the
            # retained previous frame — pairwise cost, correct flow
            fmap_p, cnet_p = engine.run_encode(s.bucket, s.last_image)
            init = np.zeros((1, H // 8, W // 8, 2), np.float32)
            self.metrics["fnet_misses"].inc()
        flow, flow_lr, fmap_c, cnet_c, iters_used = engine.run_stream(
            s.bucket, req.image1, fmap_p, cnet_p, init)
        if not (np.isfinite(flow).all() and np.isfinite(flow_lr).all()):
            # non-finite OUTPUT sentinel (inputs were validated at the
            # HTTP edge): never cache poisoned maps or a poisoned seed
            if self.nonfinite is not None:
                self.nonfinite.inc()
            log = tlm_events.current()
            if log is not None:
                log.event("nonfinite_output", session=s.id, warm=warm,
                          trace_id=(req.trace.trace_id
                                    if req.trace is not None else None))
            raise NonFiniteOutput(
                f"non-finite stream output for session {s.id} on a "
                f"{'warm' if warm else 'cold'} step")
        self.store.attach_features(s, fmap_c, cnet_c, flow_lr)
        s.last_image = req.image1
        return flow, iters_used
