"""Sessionful streaming flow: the machinery behind ``POST /v1/stream``.

Protocol (wire format parsed in http.py): a client *opens* a session with
its first frame, *advances* it one frame at a time — each advance returns
flow(prev -> cur) — and *closes* it.  Per advance the server runs ONE
encoder pass (the current frame's; the previous frame's fmap/context maps
are cached device-side in the session's SLOT of the per-bucket batch
buffers — serving/session.SlotPool) and warm-starts the recurrence from
the previous flow forward-projected along itself
(ops/warmstart.warm_start_seed — RAFT's own Sintel video protocol), so a
``converge:eps`` iteration policy exits in a fraction of the cold count.

**Continuous batching** (ROADMAP item 1, the Ragged-Paged-Attention
recipe from PAPERS.md): advances are keyed per BUCKET in the admission
queue, so concurrent stream steps from *different* sessions coalesce —
up to max_batch / max_wait, exactly like pairwise requests — into ONE
batched stream executable (models/raft.make_stream_batch_step_fn) that
gathers each row's cached maps + warm-start seed from its pool slot,
advances every session in one device call, and scatters the updated
rows back.  Rows join and leave the batch every step as sessions open,
advance and close; padding rows are inactive (scratch slot, converged
from iteration 0, excluded from all metrics).  Session opens and the
cold-restart path stay solo calls (keyed per session): they run the
``encode`` executable, which has no batch-mates to share.

Stream steps ride the SAME admission queue and batcher thread as
``/v1/flow`` (bounded depth -> 429, deadlines -> 504, graceful drain);
the session lock serializes frames within a session (a concurrent
advance on the same session answers 409 rather than reordering the
recurrence) — which is also why a coalesced group can never hold the
same session twice, so the commit scatter's real slot indices are
always unique.

Thread model (SERVING.md "Threading model"): the handler thread holds
``Session.lock`` across the WHOLE advance — including ``queue.submit``
(which takes the queue lock) and the blocking wait — which is why the
declared hierarchy orders ``Session.lock`` OUTSIDE
``RequestQueue._lock``.  The coordinator itself holds no lock: session
state is mutated only in :meth:`execute`/:meth:`execute_group` on the
batcher thread, while the handler's session lock keeps any second frame
of the same session out; slot transitions go through the store
(store lock → pool lock, the declared edge).

Failure containment, per ROW of a batched step: a warm row that faults —
the batched call raising, or that row's output failing the non-finite
sentinel (e.g. a poisoned slot) — is demoted and healed through the SAME
transparent cold-restart path an evicted session takes, in the same
advance; its co-batched neighbors keep their warm results.  This is the
stream path's form of poisoned-row isolation: the pairwise path bisects
because it has no finer fallback, the stream path degrades straight to
per-row cold restarts (finer blame, bounded at two engine calls per
row).  A cold attempt that faults is terminal for that frame only.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..data.pipeline import embed_to_shape, pad_to_shape
from ..ops.warmstart import warm_start_seed
from ..telemetry import events as tlm_events
from ..telemetry import spans as tlm_spans
from .batcher import NonFiniteOutput
from .queue import (DeadlineExceeded, Draining, RejectedError, Request,
                    RequestQueue)
from .session import Session, SessionStore


class UnknownSession(RejectedError):
    """Session id never existed, was closed, or aged out (TTL) — reopen."""
    http_status = 404


class SessionBusy(RejectedError):
    """A frame for this session is already in flight (advances are
    strictly sequential: frame t's flow seeds frame t+1)."""
    http_status = 409


class StreamRequest(Request):
    """One stream step in flight.  ``bucket`` is the queue key —
    advances key per BUCKET (``("stream", H, W)``) so concurrent steps
    from different sessions coalesce into one batched device call, while
    opens key per session (``("stream-open", sid)``): they run the solo
    encode executable and have nothing to coalesce with.  Neither key
    ever collides with a pairwise ``(H, W)`` bucket."""

    __slots__ = ("session", "stream_op", "warm", "frame", "abandoned")

    def __init__(self, session: Session, op: str, image_padded, pads,
                 deadline: float,
                 qbucket: Optional[Tuple[int, int]] = None):
        # qbucket: the (H, W) the advance key coalesces on — the
        # session's routed bucket in dense mode; under --ragged the
        # coordinator passes the shared max box, so advances from
        # DIFFERENT resolutions land in one FIFO and one batched step.
        kb = tuple(session.bucket if qbucket is None else qbucket)
        key = (("stream",) + kb if op == "advance"
               else ("stream-open", session.id))
        super().__init__(image_padded, None, key, pads, deadline,
                         rbucket=tuple(session.bucket))
        self.session = session
        self.stream_op = op              # "open" | "advance"
        self.warm = False                # set at execute time
        self.frame = 0
        # set by the handler when wait() gives up (batcher stalled past
        # the deadline margin): the batcher must then SKIP the step
        # instead of mutating session state after the session lock was
        # released — a late orphaned step would otherwise consume the
        # frame a client retry is about to resubmit
        self.abandoned = False


class StreamCoordinator:
    """Owns the session store + slot pool policy and the stream-step
    device recipe.

    Handler threads call :meth:`open`/:meth:`advance`/:meth:`close`
    (validate, lock the session, enqueue, block); the batcher thread
    calls :meth:`execute` (opens) and :meth:`execute_group` (coalesced
    advances) — the only places device state moves.
    """

    def __init__(self, store: SessionStore, sconfig, queue: RequestQueue,
                 metrics: Dict, count_fn, faults=None, nonfinite=None,
                 breaker=None, tracer=None):
        self.store = store
        self.pool = store.pool
        self.sconfig = sconfig
        self.queue = queue
        self.metrics = metrics           # make_stream_metrics families
        self.count = count_fn            # FlowServer.count_request
        self.faults = faults             # chaos injector (session arm)
        self.nonfinite = nonfinite       # raft_nonfinite_outputs_total
        self.breaker = breaker           # CircuitBreaker or None
        self.tracer = tracer             # telemetry.spans.Tracer or None
        # ragged mixed-resolution mode (SERVING.md "Ragged serving"):
        # every device call runs at the shared max-box arena bucket with
        # per-row live sizes; sessions keep their ROUTED bucket for
        # protocol/routing purposes
        self.ragged = bool(getattr(sconfig, "ragged", False))
        self.dev_box = sconfig.max_box if self.ragged else None

    def _dev(self, s: Session) -> Tuple[int, int]:
        """The bucket device calls run at: the session's routed bucket,
        or the shared max-box arena under --ragged."""
        return s.bucket if self.dev_box is None else self.dev_box

    def _mask_seed(self, seed: np.ndarray,
                   bucket: Tuple[int, int]) -> np.ndarray:
        """Zero a warm-start seed outside the session's live 1/8-scale
        extent: warm_start_seed forward-splats flow along itself, so
        un-masked dead-embedding flow could leak into the live region of
        the NEXT step's init (deterministically, but noise all the
        same)."""
        if self.dev_box is None:
            return seed
        bh, bw = bucket
        seed = np.asarray(seed).copy()
        seed[..., bh // 8:, :, :] = 0.0
        seed[..., :, bw // 8:, :] = 0.0
        return seed

    def _demote_shared(self, reason: str = "degraded") -> None:
        """Ragged twin of ``store.demote_bucket``: a failed commit killed
        the ARENA buffers every resolution shares, so every declared
        bucket's sessions must demote (in-flight included — same
        single-batcher-thread safety argument)."""
        for b in self.sconfig.buckets:
            self.store.demote_bucket(tuple(b), reason)

    # -- handler-thread API ------------------------------------------------

    def open(self, image: np.ndarray, deadline_ms: Optional[float],
             trace_id: Optional[str] = None,
             finish_trace: bool = True) -> Dict:
        from .http import BadRequest    # circular-free: http imports us not
        self.store.sweep()
        h, w = image.shape[0], image.shape[1]
        bucket = self.sconfig.route(h, w)
        if bucket is None:
            raise BadRequest(
                f"no declared bucket fits ({h}, {w}); buckets: "
                f"{[f'{bh}x{bw}' for bh, bw in self.sconfig.buckets]}")
        s = self.store.open(bucket)
        try:
            with s.lock:
                req = self._run_step(s, "open", image, deadline_ms,
                                     trace_id=trace_id,
                                     finish_trace=finish_trace)
        except BaseException:
            # no half-open sessions — but close AFTER releasing s.lock:
            # store.close takes the store lock, which the hierarchy orders
            # OUTSIDE the session lock (the id never reached the client,
            # so nothing can race the record between release and close)
            self.store.close(s.id)
            raise
        self.metrics["opens"].inc()
        res = {"session": s.id, "frame": 0,
               "meta": {"bucket": list(bucket)}, "_trace": req.trace,
               "_finished_at": req.finished_at}
        if req.trace is not None:
            res["meta"]["trace_id"] = req.trace.trace_id
        return res

    def advance(self, sid: Optional[str], image: np.ndarray,
                deadline_ms: Optional[float],
                trace_id: Optional[str] = None,
                finish_trace: bool = True) -> Dict:
        from .http import BadRequest
        self.store.sweep()
        s = self.store.get(sid) if sid else None
        if s is None:
            self.count("unknown_session")
            raise UnknownSession(
                f"unknown session {sid!r} (closed, expired after "
                f"{self.sconfig.session_ttl_s:.0f}s idle, or never "
                f"opened) — open a new one")
        if not s.lock.acquire(blocking=False):
            self.count("session_busy")
            raise SessionBusy(f"session {sid} already has a frame in "
                              f"flight; advances are sequential")
        try:
            h, w = image.shape[0], image.shape[1]
            if self.sconfig.route(h, w) != s.bucket:
                raise BadRequest(
                    f"frame ({h}, {w}) does not route to this session's "
                    f"bucket {s.bucket}; resolution changes mid-stream "
                    f"need a new session")
            req = self._run_step(s, "advance", image, deadline_ms,
                                 trace_id=trace_id,
                                 finish_trace=finish_trace)
        finally:
            s.lock.release()
            # a close() that raced this advance deferred the slot free
            # to us (see SessionStore.close)
            self.store.reclaim_if_closed(s)
        meta = {"bucket": list(s.bucket), "warm": req.warm,
                "batch_real": req.batch_real,
                "batch_padded": req.batch_padded}
        if req.iters_used is not None:
            meta["iters_used"] = req.iters_used
        if req.trace is not None:
            meta["trace_id"] = req.trace.trace_id
        return {"session": s.id, "frame": req.frame, "flow": req.result,
                "meta": meta, "_trace": req.trace,
                "_finished_at": req.finished_at}

    def close(self, sid: Optional[str]) -> Dict:
        s = self.store.close(sid) if sid else None
        if s is None:
            self.count("unknown_session")
            raise UnknownSession(f"unknown session {sid!r}")
        return {"session": sid, "closed": True, "frames": s.frames}

    def _run_step(self, s: Session, op: str, image: np.ndarray,
                  deadline_ms: Optional[float],
                  trace_id: Optional[str] = None,
                  finish_trace: bool = True) -> StreamRequest:
        """Pad, enqueue, block until the batcher resolves — the stream
        twin of FlowServer.infer, same deadline/shed/drain accounting and
        the same trace lifecycle: the trace closes HERE on every failure
        path (status from the exception); on success the HTTP handler
        finishes it after the respond span (``finish_trace=False``), or
        this method does for direct callers."""
        from .http import BadRequest
        tr = (self.tracer.start("stream", trace_id)
              if self.tracer is not None else None)
        t0 = time.monotonic()
        try:
            dl = (self.sconfig.default_deadline_ms if deadline_ms is None
                  else min(deadline_ms, self.sconfig.default_deadline_ms))
            if dl <= 0:
                raise BadRequest(f"deadline_ms must be positive, got {dl}")
            imp, pads = pad_to_shape(image[None].astype(np.float32),
                                     s.bucket)
            if self.dev_box is not None:
                # ragged: zero-embed the routed-bucket frame corner-
                # anchored into the max-box arena and fold the embedding
                # into pads, so unpad() recovers the original resolution
                # straight from the max-box flow
                (bh, bw), (mh, mw) = s.bucket, self.dev_box
                imp = embed_to_shape(imp, self.dev_box)
                t, b_, l_, r_ = pads
                pads = (t, b_ + mh - bh, l_, r_ + mw - bw)
            req = StreamRequest(s, op, imp, pads,
                                deadline=time.monotonic() + dl / 1000.0,
                                qbucket=self.dev_box)
            req.trace = tr
            if tr is not None:
                tr.span("admit", t0, time.monotonic(), op=op,
                        session=s.id)
            try:
                self.queue.submit(req)
            except Draining:
                self.count("draining")
                raise
            except Exception:           # QueueFull: overload shed, HTTP 429
                self.count("shed")
                raise
            try:
                req.wait(timeout=dl / 1000.0 + max(30.0, dl / 1000.0))
            except DeadlineExceeded:
                # the step may still be queued (or mid-execution on a
                # stalled device): mark it so the batcher drops it instead
                # of advancing the session after this thread releases its
                # lock
                req.abandoned = True
                if req.error is None:
                    self.count("timeout")
                raise
        except BaseException as e:
            if tr is not None:
                # stamp-if-absent (see FlowServer.infer): never overwrite
                # another request's id on a shared exception instance
                if getattr(e, "trace_id", None) is None:
                    e.trace_id = tr.trace_id
                tr.finish(tlm_spans.status_of(e))
            raise
        if finish_trace and tr is not None:
            tr.finish()
        return req

    # -- batcher-thread API ------------------------------------------------

    def execute(self, req: StreamRequest, engine):
        """Run one SOLO stream step on the device (session open, or a
        lone advance routed outside the group path).  Returns (padded
        flow or None, iters_used or None); all session/cache mutation
        happens here or in :meth:`execute_group`, on the single thread
        that owns the device."""
        s = req.session
        if req.stream_op == "open":
            fmap, cnet = engine.run_encode(self._dev(s), req.image1)
            self._attach(s, engine, fmap, cnet, flow_lr=None)
            s.last_image = req.image1
            return None, None
        [(flow, iters_used, err)] = self.execute_group([req], engine)
        if err is not None:
            raise err
        return flow, iters_used

    def execute_group(self, group: List[StreamRequest], engine):
        """Advance a coalesced same-bucket group of sessions: ONE batched
        device call for the warm rows (gather slots → step → masked
        commit), solo cold restarts for demoted rows and for warm rows
        that faulted (the per-row degradation ladder — see the module
        docstring).  Returns ``[(padded flow, iters_used, err)]`` aligned
        with ``group``; exactly one of flow/err is set per row.  Session
        host state (frames, last_image) moves only for rows that
        succeeded."""
        if self.faults is not None:
            for r in group:
                self.faults.corrupt_session(r.session, engine)
        results: List[Optional[tuple]] = [None] * len(group)
        warm_idx = [i for i, r in enumerate(group)
                    if r.session.has_features]
        heal_idx: List[int] = []
        if warm_idx:
            rows = self._warm_batch([group[i] for i in warm_idx], engine)
            for i, row in zip(warm_idx, rows):
                if row is None:          # faulted warm row: degrade, heal
                    heal_idx.append(i)
                else:
                    results[i] = row
        cold_idx = [i for i, r in enumerate(group)
                    if not r.session.has_features and i not in heal_idx]
        for i in sorted(cold_idx + heal_idx):
            r = group[i]
            try:
                flow, iters_used = self._cold_advance(r.session, r, engine)
                r.warm = False
                if iters_used is not None:
                    iters_used = int(np.asarray(iters_used).reshape(-1)[0])
                results[i] = (flow, iters_used, None)
            except Exception as e:
                if self.breaker is not None:
                    self.breaker.record(False)
                results[i] = (None, None, e)
        for r, (flow, _iters, err) in zip(group, results):
            if err is None:
                r.session.frames += 1
                r.frame = r.session.frames
                self.metrics["frames"].inc()
        return results

    def _warm_batch(self, reqs: List[StreamRequest], engine):
        """One batched stream step over the warm rows.  Returns a list
        aligned with ``reqs``: ``(padded flow, iters_used, None)`` for
        rows whose output passed the sentinel (their slots are
        committed), or None for rows that must heal cold (their slots
        are dropped; nothing poisoned is ever cached)."""
        s0 = reqs[0].session
        bucket = self._dev(s0)
        n = len(reqs)
        padded = self.sconfig.pad_batch_to(min(n, self.sconfig.max_batch))
        images = np.concatenate([r.image1 for r in reqs]
                                + [reqs[-1].image1] * (padded - n))
        slots = np.asarray([r.session.slot for r in reqs]
                           + [self.pool.scratch] * (padded - n), np.int32)
        active = np.asarray([True] * n + [False] * (padded - n), bool)
        sizes = None
        if self.dev_box is not None:
            # per-row live extents: each session's ROUTED bucket (filler
            # rows repeat the last, matching their repeated pixels)
            sizes = np.asarray([r.session.bucket for r in reqs]
                               + [reqs[-1].session.bucket] * (padded - n),
                               np.int32)
        try:
            flow, flow_lr, fmap_rows, cnet_rows, iters_used = \
                engine.run_stream_batch(bucket, images, slots, active,
                                        sizes=sizes)
        except Exception:
            # the batched call itself faulted: every row degrades to the
            # cold-restart path (the solo semantics, batched — no retry:
            # a warm step has a finer fallback than re-running the whole
            # group, and the cold heal isolates the guilty row).  The
            # failed call still counts against the breaker: it measures
            # engine-call health, and a 100%-warm-failure mode must stay
            # visible even though every advance heals.
            if self.breaker is not None:
                self.breaker.record(False)
            for r in reqs:
                self._degrade(r)
            return [None] * n
        if self.breaker is not None:
            self.breaker.record(True)
        h, w = bucket
        row_ok = np.array([np.isfinite(flow[i]).all()
                           and np.isfinite(flow_lr[i]).all()
                           for i in range(n)], bool)
        # commit BEFORE touching host state, AFTER the sentinel: finite
        # rows scatter their updated maps + next-frame warm-start seed
        # into their slots; rejected and padding rows write their old
        # values back (mask), so a poisoned output can never be cached
        seeds = np.zeros((padded, h // 8, w // 8, 2), np.float32)
        for i in np.flatnonzero(row_ok):
            seeds[i] = self._mask_seed(
                warm_start_seed(flow_lr[i:i + 1], (h // 8, w // 8))[0],
                reqs[i].session.bucket)
        mask = active.copy()
        mask[:n] &= row_ok
        try:
            engine.commit_stream(bucket, slots, fmap_rows, cnet_rows,
                                 seeds, mask)
        except Exception:
            # a failed commit leaves the (donated) bucket buffers dead;
            # commit_stream already rebuilt them zeroed — now demote
            # EVERY session of the bucket, in-flight/queued ones
            # included (demote_bucket overrides the skip-the-locked
            # convention precisely because a kept slot would gather the
            # zeros and serve finite garbage), then heal this group cold.
            # Under --ragged every resolution shares the arena buffers,
            # so EVERY declared bucket demotes.
            if self.dev_box is not None:
                self._demote_shared()
            else:
                self.store.demote_bucket(bucket)
            for r in reqs:
                self._degrade(r)
            return [None] * n
        out = []
        for i, r in enumerate(reqs):
            if not row_ok[i]:
                if self.nonfinite is not None:
                    self.nonfinite.inc()
                log = tlm_events.current()
                if log is not None:
                    log.event("nonfinite_output", session=r.session.id,
                              warm=True,
                              trace_id=(r.trace.trace_id
                                        if r.trace is not None else None))
                self._degrade(r)
                out.append(None)
                continue
            r.session.last_image = r.image1
            r.warm = True
            self.metrics["fnet_hits"].inc()
            out.append((flow[i:i + 1],
                        None if iters_used is None else int(iters_used[i]),
                        None))
        return out

    def _degrade(self, req: StreamRequest) -> None:
        """Drop one faulted warm row's slot so its heal (and every later
        advance until re-promotion) runs the transparent cold-restart
        path; the client still gets a 200, the trace says what it cost."""
        self.store.demote(req.session, "degraded")
        self.metrics["degraded"].inc()
        if req.trace is not None:
            # degraded outranks ok and is always recorder-retained
            req.trace.set_status(tlm_spans.DEGRADED)

    def _cold_advance(self, s: Session, req: StreamRequest, engine):
        """Cold two-encoder restart from the retained previous frame —
        pairwise cost, correct flow.  Session state (slot, last_image) is
        mutated only AFTER the output passes the non-finite sentinel, so
        a faulted attempt leaves the session exactly where it was."""
        ab = self._dev(s)
        H, W = ab
        fmap_p, cnet_p = engine.run_encode(ab, s.last_image)
        init = np.zeros((1, H // 8, W // 8, 2), np.float32)
        self.metrics["fnet_misses"].inc()
        sizes = (np.asarray([s.bucket], np.int32)
                 if self.dev_box is not None else None)
        flow, flow_lr, fmap_c, cnet_c, iters_used = engine.run_stream(
            ab, req.image1, fmap_p, cnet_p, init, sizes=sizes)
        if not (np.isfinite(flow).all() and np.isfinite(flow_lr).all()):
            # non-finite OUTPUT sentinel (inputs were validated at the
            # HTTP edge): never cache poisoned maps or a poisoned seed
            if self.nonfinite is not None:
                self.nonfinite.inc()
            log = tlm_events.current()
            if log is not None:
                log.event("nonfinite_output", session=s.id, warm=False,
                          trace_id=(req.trace.trace_id
                                    if req.trace is not None else None))
            raise NonFiniteOutput(
                f"non-finite stream output for session {s.id} on a "
                f"cold step")
        self._attach(s, engine, fmap_c, cnet_c, flow_lr)
        s.last_image = req.image1
        return flow, iters_used

    def _attach(self, s: Session, engine, fmap, cnet, flow_lr) -> None:
        """Install fresh maps + the next advance's warm-start seed into
        the session's slot (promoting it — LRU demotion happens inside
        the store if the pool is at capacity).  ``promote`` returning
        None (every slot pinned by an in-flight session) leaves the
        session cold: correct, just the pairwise cost next frame.  A
        FAILED commit must not fail the advance either — the flow is
        already computed and correct — but its donated buffers are dead
        (rebuilt zeroed by the engine), so the whole bucket demotes
        before anything can gather the zeros."""
        slot = self.store.promote(s)
        if slot is None:
            return
        ab = self._dev(s)
        H, W = ab
        seed = self._mask_seed(warm_start_seed(flow_lr, (H // 8, W // 8)),
                               s.bucket)
        try:
            engine.commit_row(ab, slot, fmap, cnet, seed)
            self.pool.set_extent(s.bucket, slot, s.bucket)
        except Exception:
            if self.dev_box is not None:
                self._demote_shared()
            else:
                self.store.demote_bucket(s.bucket)
