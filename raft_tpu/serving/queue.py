"""Admission queue: bounded, deadline-aware, per-bucket FIFO.

One global depth bound gives the backpressure contract — a submission past
``queue_depth`` waiting requests is shed immediately with :class:`QueueFull`
(the HTTP layer turns that into 429) instead of growing an unbounded backlog
whose tail would all miss its deadlines anyway.  Inside the bound, requests
are FIFO per resolution bucket so the micro-batcher can coalesce same-shape
neighbors without head-of-line blocking across buckets.

Deadlines use ``time.monotonic``.  A request whose deadline passes while it
still waits is completed with :class:`DeadlineExceeded` (HTTP 504) by the
batcher's purge pass — it never reaches the device.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..lint.concurrency import guarded_by
from ..telemetry.watchdogs import watched_lock


class RejectedError(Exception):
    """Base: request refused before reaching the device.  ``retry_after``
    (seconds, None = don't advertise) rides to the HTTP layer as a
    ``Retry-After`` header on 429/503 responses — the docstrings always
    promised "retry with backoff"; now the wire says when.
    ``trace_status`` is the request-trace disposition this rejection maps
    to (telemetry/spans.py status taxonomy)."""
    http_status = 500
    retry_after: Optional[float] = None
    trace_status = "shed"


class QueueFull(RejectedError):
    """Admission queue at capacity — shed, try again later (429)."""
    http_status = 429
    retry_after = 1.0


class Draining(RejectedError):
    """Server is shutting down; no new work accepted (503)."""
    http_status = 503
    retry_after = 5.0


class DeadlineExceeded(RejectedError):
    """Deadline passed while the request waited (504)."""
    http_status = 504
    trace_status = "timeout"


_ids = itertools.count(1)


class Request:
    """One image pair in flight.  The submitting (HTTP handler) thread
    blocks on ``wait()``; the batcher thread delivers via ``resolve``/
    ``fail``."""

    __slots__ = ("id", "image1", "image2", "bucket", "rbucket", "pads",
                 "deadline", "enqueued_at", "dequeued_at", "finished_at",
                 "_done", "result", "error", "batch_real", "batch_padded",
                 "iters_used", "trace")

    def __init__(self, image1: np.ndarray, image2: np.ndarray,
                 bucket: Tuple[int, int], pads: Tuple[int, int, int, int],
                 deadline: float,
                 rbucket: Optional[Tuple[int, int]] = None):
        self.id = next(_ids)
        self.image1 = image1          # padded [1, BH, BW, 3] float32
        self.image2 = image2
        self.bucket = bucket
        # routed bucket: the resolution this request was routed to before
        # any ragged max-box embedding.  == bucket in dense mode; under
        # --ragged, bucket is the shared max box (so the FIFO coalesces
        # across resolutions) and rbucket is the live extent the batcher
        # passes as the row's sizes.
        self.rbucket = bucket if rbucket is None else rbucket
        self.pads = pads
        self.deadline = deadline      # monotonic seconds
        self.enqueued_at = time.monotonic()
        self.dequeued_at: Optional[float] = None
        # stamped at resolve/fail: the respond span starts here, so the
        # event-wake gap (resolve -> handler thread scheduled) is
        # attributed to response delivery, not lost
        self.finished_at: Optional[float] = None
        self._done = threading.Event()
        self.result: Optional[np.ndarray] = None   # unpadded [h, w, 2]
        self.error: Optional[BaseException] = None
        self.batch_real = 0
        self.batch_padded = 0
        # GRU iterations this request's sample actually spent (set by the
        # batcher under --iters-policy converge:*; None under 'fixed')
        self.iters_used: Optional[int] = None
        # request-scoped trace (telemetry.spans.RequestTrace) attached by
        # the server at admission; None when tracing is sampled out
        self.trace = None

    @property
    def done(self) -> bool:
        """Resolved or failed (the supervisor's in-flight check)."""
        return self._done.is_set()

    def resolve(self, flow: np.ndarray) -> None:
        self.result = flow
        self.finished_at = time.monotonic()
        self._done.set()

    def fail(self, err: BaseException) -> None:
        self.error = err
        self.finished_at = time.monotonic()
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise DeadlineExceeded(f"request {self.id} still pending after "
                                   f"{timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class RequestQueue:
    """Bounded multi-bucket FIFO shared by submitters and the batcher.

    Thread model: HTTP handler threads ``submit``; the batcher thread
    ``take_batch``es (and waits on ``_cond``, which wraps — i.e. aliases —
    ``_lock``).  Everything mutable is guarded by ``_lock``; a stream
    handler submits while holding its session lock, so in the declared
    hierarchy this lock sits INSIDE ``Session.lock`` (SERVING.md)."""

    _by_bucket = guarded_by("_lock")
    _size = guarded_by("_lock")
    _closed = guarded_by("_lock")

    def __init__(self, depth: int):
        self.depth = depth
        self._lock = watched_lock("RequestQueue._lock")
        self._cond = threading.Condition(self._lock)
        self._by_bucket: Dict[Tuple[int, int], List[Request]] = {}
        self._size = 0
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return self._size

    def submit(self, req: Request) -> None:
        """Admit or shed.  Raises QueueFull / Draining; never blocks."""
        with self._lock:
            if self._closed:
                raise Draining("server is draining; not accepting requests")
            if self._size >= self.depth:
                raise QueueFull(f"queue at capacity ({self.depth} waiting)")
            self._by_bucket.setdefault(req.bucket, []).append(req)
            self._size += 1
            self._cond.notify()

    def close(self) -> None:
        """Stop admitting; wakes the batcher so it can drain and exit."""
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @guarded_by("_lock")
    def _purge_expired_locked(self, now: float) -> List[Request]:
        expired = []
        for bucket, fifo in list(self._by_bucket.items()):
            keep = []
            for r in fifo:
                (expired if r.deadline <= now else keep).append(r)
            if len(keep) != len(fifo):
                # drop emptied keys: stream requests key per SESSION, so a
                # long-lived server would otherwise accrete one dead list
                # per session ever seen
                if keep:
                    self._by_bucket[bucket] = keep
                else:
                    del self._by_bucket[bucket]
        self._size -= len(expired)
        return expired

    def take_batch(self, max_batch: int, max_wait: float):
        """Batcher side: block until a batch is ready, then pop it.

        Returns (batch, expired) where ``batch`` is a same-bucket FIFO run
        of up to ``max_batch`` requests (None when the queue closed empty)
        and ``expired`` are requests whose deadline passed while queued —
        the caller fails those with DeadlineExceeded.  A batch is ready
        when some bucket holds max_batch requests, when the oldest waiting
        request has aged ``max_wait`` seconds, or when the queue is closed
        (drain: flush immediately, ignore max_wait).
        """
        with self._lock:
            while True:
                now = time.monotonic()
                expired = self._purge_expired_locked(now)
                best, best_head = None, None
                for bucket, fifo in self._by_bucket.items():
                    if not fifo:
                        continue
                    head = fifo[0].enqueued_at
                    if best is None or head < best_head:
                        best, best_head = bucket, head
                if best is not None:
                    fifo = self._by_bucket[best]
                    full = len(fifo) >= max_batch
                    aged = now - best_head >= max_wait
                    if full or aged or self._closed:
                        batch = fifo[:max_batch]
                        rest = fifo[len(batch):]
                        if rest:
                            self._by_bucket[best] = rest
                        else:           # see _purge_expired_locked
                            del self._by_bucket[best]
                        self._size -= len(batch)
                        for r in batch:
                            r.dequeued_at = now
                        return batch, expired
                    timeout = best_head + max_wait - now
                elif self._closed:
                    return None, expired
                else:
                    timeout = None
                if expired:
                    # deliver timeouts promptly rather than after the wait
                    return [], expired
                self._cond.wait(timeout)

    def drain_remaining(self) -> List[Request]:
        """Pop everything still queued (used on hard shutdown)."""
        with self._lock:
            out = [r for fifo in self._by_bucket.values() for r in fifo]
            self._by_bucket.clear()
            self._size = 0
            return out
