"""Serving configuration: the knobs of the micro-batching inference service.

Every shape the server will ever put on the device is declared HERE, up
front: the resolution buckets and the batch steps.  The engine warms (AOT-
compiles) the full (bucket x batch-step) grid before the first request, so
steady-state serving never traces or compiles — the raftlint R2 discipline
(no recompile storms) enforced structurally rather than by convention.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..config import parse_iters_policy


def parse_buckets(spec: str) -> Tuple[Tuple[int, int], ...]:
    """Parse a CLI bucket spec like ``"432x1024,240x432"`` into an (H, W)
    tuple list.  Each side must be a positive multiple of 8 (the RAFT
    stride contract, models/raft.py)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            h, w = (int(v) for v in part.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad bucket {part!r}: expected HxW, e.g. 432x1024")
        if h <= 0 or w <= 0 or h % 8 or w % 8:
            raise ValueError(f"bucket {part!r}: H and W must be positive "
                             f"multiples of 8")
        out.append((h, w))
    if not out:
        raise ValueError(f"no buckets in spec {spec!r}")
    return tuple(out)


def default_batch_steps(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to (and always including) max_batch: every padded
    device call hits one of these sizes, so the compile grid stays
    O(log max_batch) per bucket instead of O(max_batch)."""
    steps = []
    s = 1
    while s < max_batch:
        steps.append(s)
        s *= 2
    steps.append(max_batch)
    return tuple(steps)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static configuration of the serving stack (see SERVING.md)."""

    # Pre-declared resolution buckets, largest-wins routing NOT — each
    # request routes to the SMALLEST bucket that contains it (minimal
    # padding); inputs larger than every bucket are rejected with 400.
    buckets: Tuple[Tuple[int, int], ...] = ((432, 1024),)
    # Micro-batcher: coalesce same-bucket requests up to max_batch, or until
    # the oldest queued request has waited max_wait_ms — whichever first.
    max_batch: int = 4
    max_wait_ms: float = 5.0
    # Batch sizes actually compiled/executed; a coalesced group is padded up
    # to the next step (occupancy = real / padded).  None = powers of two
    # up to max_batch (default_batch_steps).
    batch_steps: Tuple[int, ...] = None  # type: ignore[assignment]
    # Admission control: at most this many requests WAITING (in-flight
    # batches excluded); submissions beyond it are shed with 429 instead of
    # queueing unboundedly.
    queue_depth: int = 128
    # Per-request deadline (client can lower per call, never raise): a
    # request still queued past its deadline is dropped with 504 — late
    # answers are worthless and computing them steals capacity.
    default_deadline_ms: float = 2000.0
    # HTTP endpoint. port 0 = ephemeral (the bound port is printed and
    # available as FlowServer.port — what the bench and tests use).
    host: str = "127.0.0.1"
    port: int = 8000
    # Shard each device call over N local devices (parallel.make_dp_eval_fn);
    # batch steps are rounded up to multiples of N.  1 = single device.
    dp_devices: int = 1
    # AOT-compile every (bucket, batch-step) executable before accepting
    # traffic.  Off skips straight to lazy compiles (first request per shape
    # pays the compile — useful only for quick experiments).
    warmup: bool = True
    # Iteration policy of the served model (config.parse_iters_policy):
    # None inherits the model config; 'converge:eps[:min_iters]' turns on
    # per-sample early exit — shapes stay static so the batcher and the
    # warm compile grid are untouched, but the policy IS part of the
    # engine-cache key: every warmed executable is pinned to the policy it
    # was compiled under, and each request's iterations-used lands in the
    # raft_iters_used histogram on /metrics.
    iters_policy: Optional[str] = None
    # Streaming (/v1/stream, SERVING.md): at most this many video sessions
    # hold device-resident feature maps; past it the LRU session's maps
    # are evicted and its next advance degrades transparently to a cold
    # two-encoder restart.  0 disables the endpoint (and its warmup
    # executables) entirely.
    max_sessions: int = 64
    # Sessions idle longer than this are reaped outright (record included);
    # advancing a reaped id is a 404 — the client reopens.
    session_ttl_s: float = 300.0
    # Chaos harness (serving/faults.py): a fault-injection spec like
    # "seed=11,engine_error=0.05,nan=0.03,kill=0.01" arms the injector
    # (--chaos / RAFT_TPU_CHAOS).  None (default) = off, zero overhead.
    chaos: Optional[str] = None
    # Circuit breaker (serving/breaker.py): when the device-call error
    # rate over the last `breaker_window` calls reaches
    # `breaker_threshold` (with at least `breaker_min_volume` observed),
    # the breaker opens for `breaker_cooldown_s`: requests shed with 503
    # + Retry-After and streaming sessions demote to the cold-restart
    # path; then half-open probes decide recovery.  window 0 disables.
    breaker_window: int = 64
    breaker_threshold: float = 0.5
    breaker_min_volume: int = 8
    breaker_cooldown_s: float = 5.0
    # Request-scoped tracing (telemetry/spans.py, OBSERVABILITY.md):
    # fraction of completed request traces RETAINED (flight recorder +
    # run-log `trace` events) — error-status traces are always retained
    # while tracing is on.  Every request still records spans (the
    # response's meta.timings), retention is what's sampled.  0 disables
    # tracing outright: no spans, no meta.timings, no SLO/flight-recorder
    # machinery, and /metrics gains none of their families.
    trace_sample: float = 1.0
    # Per-class latency objectives (SLO): a completed request slower than
    # its class objective — or terminating non-ok — burns error budget.
    # raft_slo_burn_rate{class=} = violating fraction of the last
    # `slo_window` requests / `slo_budget`; >> 1 means this replica
    # cannot meet its objective (the autoscaling signal, ROADMAP item 3).
    slo_pair_ms: float = 1000.0
    slo_stream_ms: float = 500.0
    slo_budget: float = 0.01
    slo_window: int = 256
    # Flight recorder: ring capacity (last N ok traces + up to N error
    # traces) and the auto-dump path (batcher crash / breaker open /
    # recompile watchdog / shutdown; None = /debug/traces only).
    flightrec_traces: int = 64
    flightrec_path: Optional[str] = None
    # AOT executable cache directory (serving/aot_cache.py): warmup
    # load-or-compiles serialized executables keyed by (config hash,
    # device kind, jax version) — a warm directory boots a replica with
    # ZERO XLA compiles.  The fleet points every replica at one shared
    # dir.  None disables (warmup always compiles).
    engine_cache_dir: Optional[str] = None
    # Engine-failure containment (batcher): same-group retries (with
    # backoff) before poisoned-batch bisection splits the blame.
    engine_retries: int = 1
    retry_backoff_ms: float = 20.0
    # healthz reports "degraded" for this long after a batcher crash
    # (and while the breaker is not closed) — the replica-gating signal.
    degraded_window_s: float = 30.0
    # Metric time-series (telemetry/timeseries.py, OBSERVABILITY.md):
    # a background thread samples the registry every history_interval_s
    # into a history_window-deep ring — GET /debug/history serves windowed
    # derived series (rates, delta-p95s), the anomaly sentinels evaluate
    # over it, and history_path (default <out>/metrics_ts.jsonl when the
    # server has an out dir) spills every sample with manifest provenance
    # for tlm top --replay.  0 disables sampling, the endpoint, and the
    # sentinels together.
    history_interval_s: float = 1.0
    history_window: int = 600
    history_path: Optional[str] = None
    # Anomaly sentinels (telemetry/anomaly.py): rule-driven detection over
    # the history — armed after warmup, surfaced as
    # raft_anomaly_active{rule=} + `anomaly` run-log events + a flight-
    # recorder dump on first fire.  Requires the history.  The two windows
    # feed AnomalyConfig; the smoke-scale defaults live there.
    anomaly: bool = True
    anomaly_window_s: float = 15.0
    anomaly_baseline_s: float = 60.0
    # Ragged mixed-resolution serving (SERVING.md "Ragged serving"): ONE
    # executable per (kind, batch-step, policy) serves EVERY declared bucket
    # — requests stay routed to their minimal bucket for padding accounting,
    # then ride the shared max-box executable with per-row (h, w) size
    # metadata; the batcher coalesces ACROSS buckets and mixed-resolution
    # stream sessions share one slot arena and one sbatch step.  The warmup
    # grid (and the AOT cache) shrinks from O(buckets x batch-steps) to
    # O(batch-steps).
    ragged: bool = False
    # Optional footprint budget for ragged coalescing: max live (un-padded)
    # pixels per request group, summed over the routed buckets of its
    # members.  A group exceeding it is split greedily in arrival order.
    # 0 = no cap (max_batch alone bounds the group).
    ragged_batch_pixels: int = 0

    def __post_init__(self):
        if self.batch_steps is None:
            object.__setattr__(self, "batch_steps",
                               default_batch_steps(self.max_batch))
        if not self.buckets:
            raise ValueError("at least one resolution bucket is required")
        for h, w in self.buckets:
            if h % 8 or w % 8:
                raise ValueError(f"bucket ({h}, {w}): sides must be "
                                 f"multiples of 8")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self.queue_depth}")
        if self.dp_devices < 1:
            raise ValueError(f"dp_devices must be >= 1, got {self.dp_devices}")
        if self.iters_policy is not None:
            parse_iters_policy(self.iters_policy)   # typo -> raise, up front
        if self.max_sessions < 0:
            raise ValueError(f"max_sessions must be >= 0 (0 disables "
                             f"streaming), got {self.max_sessions}")
        if not self.session_ttl_s > 0:
            raise ValueError(f"session_ttl_s must be > 0, "
                             f"got {self.session_ttl_s}")
        if self.chaos:
            from .faults import parse_chaos_spec
            parse_chaos_spec(self.chaos)    # typo -> raise, up front
        if self.breaker_window < 0:
            raise ValueError(f"breaker_window must be >= 0 (0 disables "
                             f"the breaker), got {self.breaker_window}")
        if self.breaker_window and not 0.0 < self.breaker_threshold <= 1.0:
            raise ValueError(f"breaker_threshold must be in (0, 1], "
                             f"got {self.breaker_threshold}")
        if self.breaker_window and not self.breaker_cooldown_s > 0:
            raise ValueError(f"breaker_cooldown_s must be > 0, "
                             f"got {self.breaker_cooldown_s}")
        if not 0.0 <= self.trace_sample <= 1.0:
            raise ValueError(f"trace_sample must be in [0, 1] (0 disables "
                             f"tracing), got {self.trace_sample}")
        if self.trace_sample > 0:
            if self.slo_pair_ms <= 0 or self.slo_stream_ms <= 0:
                raise ValueError("slo_pair_ms and slo_stream_ms must be "
                                 "> 0 while tracing is on")
            if not 0.0 < self.slo_budget <= 1.0:
                raise ValueError(f"slo_budget must be in (0, 1], "
                                 f"got {self.slo_budget}")
            if self.slo_window < 1 or self.flightrec_traces < 1:
                raise ValueError("slo_window and flightrec_traces must "
                                 "be >= 1")
        if self.engine_retries < 0:
            raise ValueError(f"engine_retries must be >= 0, "
                             f"got {self.engine_retries}")
        if self.retry_backoff_ms < 0 or self.degraded_window_s < 0:
            raise ValueError("retry_backoff_ms and degraded_window_s "
                             "must be >= 0")
        if self.history_interval_s < 0:
            raise ValueError(f"history_interval_s must be >= 0 (0 disables "
                             f"the metric history), got "
                             f"{self.history_interval_s}")
        if self.history_interval_s > 0 and self.history_window < 2:
            raise ValueError("history_window must be >= 2 (rates and "
                             "percentiles need two samples)")
        if self.anomaly and self.history_interval_s > 0:
            from ..telemetry.anomaly import AnomalyConfig
            AnomalyConfig(window_s=self.anomaly_window_s,
                          baseline_s=self.anomaly_baseline_s)  # validate
        steps = tuple(sorted(set(self.batch_steps)))
        if not steps or steps[0] < 1:
            raise ValueError(f"batch_steps must be positive, got {steps}")
        if self.dp_devices > 1:
            # shard_map splits the batch across devices, so every executed
            # size must divide: round each step UP to a multiple of N (the
            # documented 'padded to multiples' behavior), dedup
            n = self.dp_devices
            steps = tuple(sorted({-(-s // n) * n for s in steps}))
        if steps[-1] < self.max_batch:
            raise ValueError(f"largest batch step {steps[-1]} < max_batch "
                             f"{self.max_batch}: full batches could never run")
        object.__setattr__(self, "batch_steps", steps)
        object.__setattr__(self, "buckets", tuple(self.buckets))
        if self.ragged_batch_pixels < 0:
            raise ValueError(f"ragged_batch_pixels must be >= 0 (0 = no "
                             f"cap), got {self.ragged_batch_pixels}")
        if self.ragged and self.dp_devices > 1:
            raise NotImplementedError(
                "ragged serving under dp_devices > 1 is not wired: the "
                "ragged model entry points are single-mesh; use dense "
                "buckets or dp_devices=1")

    @property
    def max_box(self) -> Tuple[int, int]:
        """The shared ragged max box: componentwise max over the declared
        buckets (every bucket embeds corner-anchored inside it)."""
        return (max(h for h, _ in self.buckets),
                max(w for _, w in self.buckets))

    def route(self, h: int, w: int):
        """Smallest declared bucket containing (h, w), or None — minimal
        padding wins; ties break toward fewer padded pixels."""
        best = None
        for bh, bw in self.buckets:
            if h <= bh and w <= bw:
                if best is None or bh * bw < best[0] * best[1]:
                    best = (bh, bw)
        return best

    def pad_batch_to(self, n: int) -> int:
        """Smallest compiled batch step >= n (n is capped at max_batch by
        the batcher, and max_batch <= max(batch_steps) by construction)."""
        for s in self.batch_steps:
            if s >= n:
                return s
        return self.batch_steps[-1]
