"""FlowServer: queue + micro-batcher + warm engine + HTTP, composed.

Lifecycle::

    server = FlowServer(config, params, sconfig)
    server.start()            # warms the compile grid, binds the port
    ...                       # serve_forever happens on daemon threads
    server.stop(drain=True)   # 503 new work, finish what's queued, exit

``stop(drain=True)`` is the graceful path: the admission queue closes
(submissions -> 503), the batcher flushes every queued request — max_wait
is ignored once draining — and in-flight device batches run to completion
before their handler threads are released; only then does the HTTP listener
shut down.  ``drain=False`` fails queued requests immediately instead.

serve_cli is the ``python -m raft_tpu.cli -m serve`` entry point.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..config import RAFTConfig
from ..data.pipeline import embed_to_shape, pad_to_shape
from ..lint.concurrency import SERVING_LOCK_HIERARCHY
from ..telemetry import events as tlm_events
from ..telemetry import spans as tlm_spans
from ..telemetry import watchdogs as tlm_watchdogs
from ..telemetry.log import get_logger
from ..telemetry.trace import TraceWindow, stage
from .batcher import MicroBatcher
from .breaker import BreakerOpen, CircuitBreaker
from .config import ServeConfig
from .engine import InferenceEngine
from .faults import make_injector
from .http import BadRequest, make_http_server, serve_in_thread
from .metrics import (Registry, make_fault_metrics, make_robustness_metrics,
                      make_serving_metrics, make_slo_metrics,
                      make_stream_metrics)
from .queue import DeadlineExceeded, Draining, Request, RequestQueue
from .session import SessionStore
from .stream import StreamCoordinator

_log = get_logger("serve")


class BatcherSupervisor:
    """Restart-on-crash policy for the batcher daemon (the device-owning
    thread).  Before this, one stray exception escaping the loop killed
    the thread silently and every later request hung into its 504 margin;
    now a crash fails the in-flight batch (batcher._thread_main), lands
    here, is counted (``raft_batcher_restarts_total``), and the loop is
    restarted under exponential backoff.  ``/healthz`` reports
    ``degraded`` while a crash is recent (``degraded_window_s``) or the
    thread is down — the health signal ROADMAP item 3's replica gating
    needs.  Consecutive-crash backoff resets once the thread has stayed
    up a full degraded window."""

    def __init__(self, server: "FlowServer", counter=None,
                 degraded_window_s: float = 30.0,
                 max_backoff_s: float = 2.0):
        self.server = server
        self.counter = counter            # raft_batcher_restarts_total
        self.degraded_window_s = degraded_window_s
        self.max_backoff_s = max_backoff_s
        self.restarts = 0
        self.last_crash: Optional[float] = None
        self._consecutive = 0

    def on_crash(self, exc: Exception) -> None:
        """Runs on the dying batcher thread (batcher._thread_main)."""
        now = time.monotonic()
        if (self.last_crash is not None
                and now - self.last_crash > self.degraded_window_s):
            self._consecutive = 0         # stable period: backoff resets
        self.last_crash = now
        self.restarts += 1
        if self.counter is not None:
            self.counter.inc()
        _log.error(f"batcher thread crashed ({exc!r}); restart "
                   f"#{self.restarts}")
        # the crash is exactly what the flight recorder exists for: leave
        # the last N traces + every error trace as an artifact before the
        # restart muddies the water
        self.server._flight_dump("batcher_crash")
        if self.server.draining:
            self._fail_drained(exc)       # shutting down: no restart, but
            return                        # queued work must not hang
        backoff = min(0.05 * (2 ** self._consecutive), self.max_backoff_s)
        self._consecutive += 1
        time.sleep(backoff)
        if self.server.draining:
            self._fail_drained(exc)
            return
        self.server.batcher.restart()

    def _fail_drained(self, exc: Exception) -> None:
        """A crash during drain leaves the closed queue with no consumer:
        fast-fail the remainder (the drain promise is 'completes or
        errors', never 'hangs into the 504 margin')."""
        from .batcher import BatcherCrashed
        for r in self.server.queue.drain_remaining():
            self.server.count_request("error")
            r.fail(BatcherCrashed(
                f"batcher crashed during drain ({exc!r}); request "
                f"not executed"))

    @property
    def degraded(self) -> bool:
        if self.last_crash is not None and (
                time.monotonic() - self.last_crash < self.degraded_window_s):
            return True
        return not (self.server.batcher.alive or self.server.draining)


class FlowServer:
    def __init__(self, config: RAFTConfig, params, sconfig: ServeConfig,
                 iters: Optional[int] = None, engine=None,
                 verbose: bool = False,
                 trace_dir: Optional[str] = None, trace_steps: int = 4):
        self.sconfig = sconfig
        self.verbose = verbose
        # --trace generalized to serving: capture device batches 1..1+N
        # (batch 0 may pay a cold compile under --no-warmup)
        self._trace_window = TraceWindow(trace_dir, first=1,
                                         steps=trace_steps, log_fn=_log.info)
        self._device_batches = 0
        self._recompile_watch = None
        self.registry = Registry()
        self.queue = RequestQueue(sconfig.queue_depth)
        self.metrics = make_serving_metrics(
            self.registry, sconfig, queue_depth_fn=lambda: len(self.queue))
        self.registry.gauge("raft_serving_queue_limit",
                            "Admission queue capacity (backpressure bound)"
                            ).set(sconfig.queue_depth)
        # chaos harness: the injector exists only when --chaos/
        # RAFT_TPU_CHAOS arms it — a clean server carries faults=None and
        # pays one `is not None` per hook site
        self.faults = None
        if sconfig.chaos:
            self.faults = make_injector(
                sconfig.chaos,
                counter=make_fault_metrics(self.registry)["faults"],
                run_log=tlm_events.current())
        # circuit breaker: sheds 503 + Retry-After while the engine is
        # sick, demotes streaming sessions to the cold-restart path on
        # open (breaker_window=0 disables)
        self.breaker = None
        if sconfig.breaker_window > 0:
            self.breaker = CircuitBreaker(
                window=sconfig.breaker_window,
                threshold=sconfig.breaker_threshold,
                min_volume=sconfig.breaker_min_volume,
                cooldown_s=sconfig.breaker_cooldown_s,
                on_open=self._breaker_opened)
        self._robustness = make_robustness_metrics(self.registry,
                                                   breaker=self.breaker)
        self.metrics["nonfinite"] = self._robustness["nonfinite"]
        # request-scoped tracing (telemetry/spans.py): tracer + flight
        # recorder + SLO burn accounting.  trace_sample 0 disables the
        # whole plane — requests carry trace=None, every hook is one
        # `is not None`, and /metrics gains none of these families.
        self.flightrec = None
        self.slo = None
        if sconfig.trace_sample > 0:
            self.flightrec = tlm_spans.FlightRecorder(
                capacity=sconfig.flightrec_traces,
                path=sconfig.flightrec_path)
            self.slo = tlm_spans.SLOTracker(
                objectives={"pair": sconfig.slo_pair_ms / 1000.0,
                            "stream": sconfig.slo_stream_ms / 1000.0},
                budget=sconfig.slo_budget, window=sconfig.slo_window)
            make_slo_metrics(self.registry, self.slo)
        self.tracer = tlm_spans.Tracer(sample=sconfig.trace_sample,
                                       recorder=self.flightrec,
                                       slo=self.slo)
        # metric time-series + anomaly sentinels (telemetry/timeseries.py,
        # telemetry/anomaly.py — OBSERVABILITY.md "Time-series & anomaly
        # detection"): a background ring of registry snapshots feeding
        # GET /debug/history, the metrics_ts.jsonl spill, and the rule
        # sentinels (armed after warmup in start()).  history_interval_s=0
        # disables all three and keeps /metrics exposition untouched.
        self.history = None
        self.anomaly = None
        self.profile_dir: Optional[str] = None   # POST /debug/profile dest
        if sconfig.history_interval_s > 0:
            from ..telemetry.anomaly import AnomalyConfig, AnomalyMonitor
            from ..telemetry.timeseries import MetricHistory
            manifest = None
            if sconfig.history_path:
                manifest = tlm_events.run_manifest(
                    config, mode="serve", probe_device=False)
            self.history = MetricHistory(
                self.registry, interval_s=sconfig.history_interval_s,
                window=sconfig.history_window,
                path=sconfig.history_path, manifest=manifest)
            if sconfig.anomaly:
                self.anomaly = AnomalyMonitor(
                    self.history, self.registry,
                    run_log=tlm_events.current(),
                    flightrec=self.flightrec,
                    config=AnomalyConfig(
                        window_s=sconfig.anomaly_window_s,
                        baseline_s=sconfig.anomaly_baseline_s),
                    log_fn=_log.warning)
        # streaming (/v1/stream): a bounded session store + coordinator,
        # built only when declared (--max-sessions > 0) so a pairwise-only
        # server keeps its exact warmup grid and /metrics exposition
        self.streams = None
        if sconfig.max_sessions > 0:
            # under --ragged the store's slot pool must be the ARENA pool:
            # every routed bucket shares one max-box free-list, so two
            # sessions of different resolutions can never be handed the
            # same buffer row (the engine reuses this pool; its own
            # arena-aware construction only applies when none is injected)
            from .session import SlotPool
            store = SessionStore(
                sconfig.max_sessions, sconfig.session_ttl_s,
                pool=SlotPool(sconfig.max_sessions,
                              arena=(sconfig.max_box if sconfig.ragged
                                     else None)))
            stream_metrics = make_stream_metrics(self.registry, store,
                                                 buckets=sconfig.buckets)
            self.streams = StreamCoordinator(
                store, sconfig, self.queue, stream_metrics,
                self.count_request, faults=self.faults,
                nonfinite=self._robustness["nonfinite"],
                breaker=self.breaker, tracer=self.tracer)
            # the stream-step families are observed by the batcher (the
            # thread that owns the device), so they ride its metrics dict
            for k in ("steps", "step_seconds", "step_batch",
                      "step_occupancy"):
                self.metrics[f"stream_{k}"] = stream_metrics[k]
        # AOT executable cache (serving/aot_cache.py): keyed by the
        # RESOLVED config (the engine applies the sconfig iters-policy
        # override, so the cache identity must match the warmed keys)
        self.engine_cache = None
        if engine is None and sconfig.engine_cache_dir:
            import dataclasses as _dc

            from .aot_cache import EngineCache
            rconfig = config
            if sconfig.iters_policy is not None:
                rconfig = _dc.replace(config,
                                      iters_policy=sconfig.iters_policy)
            self.engine_cache = EngineCache(sconfig.engine_cache_dir,
                                            rconfig)
        # engine injection: tests drive the batching policy with stubs.
        # A streaming engine shares the coordinator's slot pool: the
        # store owns the alloc/free policy, the engine owns the device
        # buffers and the warmed gather/scatter executables.
        self.engine = engine if engine is not None else InferenceEngine(
            config, params, sconfig, iters=iters,
            stream=sconfig.max_sessions > 0, faults=self.faults,
            pool=self.streams.pool if self.streams else None,
            cache=self.engine_cache)
        self.batcher = MicroBatcher(
            self.queue, self._run_engine, sconfig.pad_batch_to,
            sconfig.max_batch, sconfig.max_wait_ms, metrics=self.metrics,
            stream_fn=self._run_stream if self.streams else None,
            stream_group_fn=(self._run_stream_group if self.streams
                             else None),
            breaker=self.breaker, faults=self.faults,
            retries=sconfig.engine_retries,
            retry_backoff_s=sconfig.retry_backoff_ms / 1000.0,
            on_crash=self._batcher_crashed,
            ragged=sconfig.ragged,
            ragged_batch_pixels=sconfig.ragged_batch_pixels)
        self.supervisor = BatcherSupervisor(
            self, counter=self._robustness["batcher_restarts"],
            degraded_window_s=sconfig.degraded_window_s)
        self._httpd = None
        self._http_thread = None
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._gauges_wired = False

    # -- engine bridge (compile-cache accounting lives server-side so a
    #    stub engine still produces hit/miss metrics when it exposes them) -

    def _run_engine(self, bucket, im1, im2, sizes=None):
        self._trace_window.on_step(self._device_batches)
        self._device_batches += 1
        before = getattr(self.engine, "compile_misses", None)
        with stage("serve/batch"):
            # sizes (ragged per-row extents) only flows when the batcher
            # passes it, so dense-mode stub engines keep their 3-arg run()
            if sizes is not None:
                out = self.engine.run(bucket, im1, im2, sizes)
            else:
                out = self.engine.run(bucket, im1, im2)
        if before is not None:
            after = self.engine.compile_misses
            if after > before:
                self.metrics["compile_misses"].inc(after - before)
            else:
                self.metrics["compile_hits"].inc()
        return out

    def _run_stream(self, req):
        """Stream-step twin of _run_engine: same trace window, same
        compile-cache accounting, one session step per call."""
        self._trace_window.on_step(self._device_batches)
        self._device_batches += 1
        before = getattr(self.engine, "compile_misses", None)
        with stage("serve/stream"):
            out = self.streams.execute(req, self.engine)
        if before is not None:
            after = self.engine.compile_misses
            if after > before:
                self.metrics["compile_misses"].inc(after - before)
            else:
                self.metrics["compile_hits"].inc()
        return out

    def _run_stream_group(self, group):
        """Continuous-batched stream step (coalesced same-bucket
        advances): one device batch, same trace window and compile-cache
        accounting as the pairwise path."""
        self._trace_window.on_step(self._device_batches)
        self._device_batches += 1
        before = getattr(self.engine, "compile_misses", None)
        with stage("serve/stream"):
            out = self.streams.execute_group(group, self.engine)
        if before is not None:
            after = self.engine.compile_misses
            if after > before:
                self.metrics["compile_misses"].inc(after - before)
            else:
                self.metrics["compile_hits"].inc()
        return out

    def engine_executables(self) -> int:
        return getattr(self.engine, "executables", 0)

    def count_request(self, status: str) -> None:
        self.metrics["requests"].labels(status).inc()

    def reload_params(self, params, tag=None) -> dict:
        """Zero-downtime weight hot-swap (POST /admin/reload): delegate to
        engine.reload — stage off-lock, probe a warm executable, flip the
        params reference atomically.  Serving never pauses; the run log
        records the swap so ``tlm`` can attribute a quality shift to it."""
        info = self.engine.reload(params, tag=tag)
        run_log = tlm_events.current()
        if run_log is not None:
            run_log.event("serve_weights_reloaded", version=info["version"],
                          tag=info.get("tag"), probed=info.get("probed"))
        return info

    def profile_capture(self, ms: float) -> dict:
        """POST /debug/profile?ms=: on-demand ``jax.profiler`` capture of
        the next ``ms`` milliseconds on a LIVE replica — no restart, no
        --trace flag decided at boot.  Single-flight (telemetry/trace.py
        ``capture_profile`` holds a process-wide lock; a concurrent
        request gets CaptureBusy → 409) and side-effect-free on the
        engine: profiling must never perturb the warm compile grid, which
        serve_bench asserts by diffing compile misses across a capture."""
        from ..telemetry.trace import capture_profile
        info = capture_profile(self.profile_dir, ms, log_fn=_log.info)
        run_log = tlm_events.current()
        if run_log is not None:
            run_log.event("profile_capture", **info)
        return info

    def prestage_cache(self) -> dict:
        """POST /admin/cache/prestage: export every in-memory executable
        (plus the manifest) into the attached AOT cache directory — the
        fleet's RollingUpdater calls this on a healthy replica before a
        weight flip so any post-swap respawn boots compile-free.  Returns
        {exported, entries, dir}; a server without a cache reports
        exported=0 with dir=None (the updater treats that as
        'nothing to pre-stage', not an error)."""
        export = getattr(self.engine, "export_cache", None)
        info = export() if export is not None else {
            "exported": 0, "entries": 0, "dir": None}
        run_log = tlm_events.current()
        if run_log is not None:
            run_log.event("serve_cache_prestaged", **info)
        return info

    # -- self-healing hooks ------------------------------------------------

    def _batcher_crashed(self, exc: Exception) -> None:
        self.supervisor.on_crash(exc)

    def _breaker_opened(self) -> None:
        """Breaker open: demote every streaming session's device features
        so nothing cached before the storm is trusted after it — their
        next advance takes the transparent cold-restart path.

        Runs under the breaker's lock (the declared breaker -> store
        hierarchy edge), so the flight-recorder dump — file I/O — is
        handed to a short-lived thread: handlers blocked in
        ``breaker.allow()`` must not wait on a disk write, and a slow
        dump must not trip the watched-lock hold budget."""
        if self.streams is not None:
            n = self.streams.store.demote_all()
            if n:
                _log.warning(f"breaker open: demoted {n} streaming "
                             f"session(s) to the cold-restart path")
        threading.Thread(target=self._flight_dump, args=("breaker_open",),
                         daemon=True, name="raft-flightrec-dump").start()

    def _flight_dump(self, reason: str) -> None:
        """Write the flight-recorder rings to their configured path (no-op
        without one — /debug/traces still serves the in-memory view)."""
        if self.flightrec is None:
            return
        try:
            path = self.flightrec.dump(reason)
        except Exception as e:  # noqa: BLE001 — a dump failure must never
            _log.warning(f"flight-recorder dump failed: {e}")  # cascade
            return
        if path:
            _log.warning(f"flight recorder: wrote {path} ({reason})")

    def _admit(self) -> None:
        """Breaker gate shared by /v1/flow and /v1/stream admission."""
        if self.breaker is None:
            return
        retry = self.breaker.allow()
        if retry is not None:
            self.count_request("breaker_open")
            raise BreakerOpen(
                f"circuit breaker open (device-call error rate over the "
                f"last {self.sconfig.breaker_window} calls reached "
                f"{self.sconfig.breaker_threshold:.0%}); retry in "
                f"{retry:.1f}s", retry_after=retry)

    def health_status(self) -> str:
        """'ok' | 'degraded' — degraded while the batcher recently
        crashed (or is down) or the breaker is not closed."""
        if self.supervisor.degraded:
            return "degraded"
        if self.breaker is not None and self.breaker.state != "closed":
            return "degraded"
        return "ok"

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if not self._gauges_wired:
            self._gauges_wired = True
            self.registry.gauge("raft_serving_compile_cache_entries",
                                "Warm executables resident",
                                fn=self.engine_executables)
            if tlm_watchdogs.lock_watch_enabled():
                # runtime lock-order validator (RAFT_TPU_LOCK_WATCH=1):
                # the serving locks were created through watched_lock, so
                # every acquisition edge is recorded — arm the declared
                # hierarchy (SERVING.md threading model) and export the
                # violation counters; the chaos drill asserts they stay 0
                v = tlm_watchdogs.export_lock_metrics(
                    self.registry, run_log=tlm_events.current())
                v.declare_order(SERVING_LOCK_HIERARCHY)
        if tlm_watchdogs.watchdogs_enabled():
            # stack-wide XLA compile listener (the serving engine's own
            # hit/miss counters see only its executables; this one also
            # catches strays — e.g. a tool jitting in-process) + live HBM
            # gauges.  Registered only when watchdogs are on, so the
            # default /metrics exposition stays byte-identical.
            self._recompile_watch = tlm_watchdogs.RecompileWatch(
                counter=self.registry.counter(
                    "raft_serving_xla_recompiles_total",
                    "XLA compiles observed after warmup (watchdog)"),
                run_log=tlm_events.current(),
                log_fn=_log.warning,
                # a post-warmup recompile is an incident: dump the traces
                on_recompile=lambda: self._flight_dump("recompile")
                ).install()
            tlm_watchdogs.hbm_gauges(self.registry, prefix="raft_serving")
        if self.sconfig.warmup and hasattr(self.engine, "warmup"):
            n = self.engine.warmup(verbose=self.verbose)
            if self.verbose:
                loaded = getattr(self.engine, "warmup_loaded", 0)
                _log.info(f"warmup built {n} executable(s) "
                          f"({loaded} loaded from the AOT cache, "
                          f"{n - loaded} compiled) in "
                          f"{self.engine.warmup_seconds:.1f}s")
        if self.engine_cache is not None:
            # bulk-fill the cache families from the warmup stats (the
            # metric registration itself is gated on the cache existing,
            # so a cacheless /metrics exposition is untouched)
            from .metrics import make_engine_cache_metrics
            fam = make_engine_cache_metrics(self.registry)
            st = self.engine_cache.stats
            for name in ("hits", "misses", "loads"):
                count = getattr(st, name)
                if count:
                    fam[name].inc(count)
            for sec in st.load_seconds:
                fam["load_seconds"].observe(sec)
        if self._recompile_watch is not None:
            self._recompile_watch.arm()
        if self.history is not None:
            self.history.sample()         # t=0 baseline before any traffic
            self.history.start()
            if self.anomaly is not None:
                # arm AFTER warmup: the compile storm and the cold queue
                # are expected — steady-state invariants start here
                self.anomaly.arm()
        self.batcher.start()
        self._httpd = make_http_server(self, self.sconfig.host,
                                       self.sconfig.port)
        self._http_thread = serve_in_thread(self._httpd)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    @property
    def url(self) -> str:
        return f"http://{self.sconfig.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Idempotent graceful (or immediate) shutdown."""
        if self._stopped.is_set():
            return
        self._draining.set()
        if not drain:
            for r in self.queue.drain_remaining():
                self.count_request("draining")
                r.fail(Draining("server shut down before this request ran"))
        self.queue.close()            # batcher drains the rest, then exits
        self.batcher.join(timeout)
        # SIGTERM/shutdown artifact: the drain is complete, so every
        # in-flight trace has closed — the dump is the final word
        self._flight_dump("shutdown")
        if self.history is not None:
            self.history.stop()           # final sample + spill close
        self._trace_window.stop()
        if self._recompile_watch is not None:
            self._recompile_watch.remove()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self._stopped.set()

    def wait(self) -> None:
        """Block until stop() completes (the CLI foreground call)."""
        while not self._stopped.is_set():
            self._stopped.wait(0.5)

    # -- request path ------------------------------------------------------

    def infer(self, im1: np.ndarray, im2: np.ndarray,
              deadline_ms: Optional[float] = None,
              trace_id: Optional[str] = None,
              finish_trace: bool = True) -> Request:
        """Route, pad, enqueue, block until resolved.  Called from HTTP
        handler threads (and directly by tests/the in-process bench).

        Trace lifecycle: a trace is minted here (or adopts the client's
        ``trace_id``) and CLOSES here on every failure path, with the
        status the exception maps to — shed, timeout, poisoned, error —
        and the exception carries ``.trace_id`` out to the HTTP layer.
        On success the HTTP handler finishes it after the respond span
        (``finish_trace=False``); direct callers let this method close it.
        """
        tr = self.tracer.start("pair", trace_id)
        t0 = time.monotonic()
        try:
            if self.draining:
                self.count_request("draining")
                raise Draining("server is draining; not accepting requests")
            self._admit()                 # breaker gate: shed 503 while open
            h, w = im1.shape[0], im1.shape[1]
            bucket = self.sconfig.route(h, w)
            if bucket is None:
                raise BadRequest(
                    f"no declared bucket fits ({h}, {w}); buckets: "
                    f"{[f'{bh}x{bw}' for bh, bw in self.sconfig.buckets]}")
            dl = self.sconfig.default_deadline_ms if deadline_ms is None \
                else min(deadline_ms, self.sconfig.default_deadline_ms)
            if dl <= 0:
                raise BadRequest(f"deadline_ms must be positive, got {dl}")
            im1p, pads = pad_to_shape(im1[None].astype(np.float32), bucket)
            im2p, _ = pad_to_shape(im2[None].astype(np.float32), bucket)
            rbucket = None
            if self.sconfig.ragged:
                # ragged: zero-embed the routed-bucket pair corner-
                # anchored into the shared max box and queue it UNDER the
                # max box, so requests of every resolution share one FIFO
                # (cross-resolution coalescing) and one executable.  The
                # embedding folds into pads so unpad() recovers (h, w)
                # straight from the max-box flow; the routed bucket rides
                # in rbucket — the batcher turns it into the row's sizes.
                rbucket = bucket
                (bh, bw), (mh, mw) = bucket, self.sconfig.max_box
                im1p = embed_to_shape(im1p, self.sconfig.max_box)
                im2p = embed_to_shape(im2p, self.sconfig.max_box)
                t, b_, l_, r_ = pads
                pads = (t, b_ + mh - bh, l_, r_ + mw - bw)
                bucket = self.sconfig.max_box
            req = Request(im1p, im2p, bucket, pads,
                          deadline=time.monotonic() + dl / 1000.0,
                          rbucket=rbucket)
            req.trace = tr
            if tr is not None:
                tr.span("admit", t0, time.monotonic(),
                        bucket=f"{bucket[0]}x{bucket[1]}")
            try:
                self.queue.submit(req)
            except Draining:
                self.count_request("draining")
                raise
            except Exception:       # QueueFull: overload shed, HTTP 429
                self.count_request("shed")
                raise
            # the generous margin past the deadline covers an in-flight
            # batch that dequeued the request just before its deadline:
            # it completes
            try:
                req.wait(timeout=dl / 1000.0 + max(30.0, dl / 1000.0))
            except DeadlineExceeded:
                if req.error is None:
                    # wait() itself timed out (batch overran / batcher
                    # stalled) — the batcher's purge never saw this one
                    self.count_request("timeout")
                raise
        except BaseException as e:
            if tr is not None:
                # stamp-if-absent: a group-wide failure can share ONE
                # exception instance across co-batched handlers, and the
                # first stamp must not be overwritten with another
                # request's id (the batcher fails shared errors with
                # per-request instances precisely so this stays unique)
                if getattr(e, "trace_id", None) is None:
                    e.trace_id = tr.trace_id
                tr.finish(tlm_spans.status_of(e))
            raise
        if finish_trace and tr is not None:
            tr.finish()
        return req

    def stream_call(self, op: str, session_id, image, deadline_ms,
                    trace_id: Optional[str] = None,
                    finish_trace: bool = True):
        """/v1/stream bridge: dispatch one open/advance/close to the
        stream coordinator (http handler threads).  ``close`` is pure
        bookkeeping and is never traced; open/advance follow the same
        trace lifecycle as :meth:`infer` (the coordinator mints it)."""
        if self.streams is None:
            raise BadRequest("streaming is disabled on this server "
                             "(--max-sessions 0); use /v1/flow")
        if self.draining:
            self.count_request("draining")
            raise Draining("server is draining; not accepting requests")
        if op == "close":
            # closing is bookkeeping, never a device call: always allowed
            return self.streams.close(session_id)
        self._admit()                     # breaker gate: shed 503 while open
        if op == "open":
            res = self.streams.open(image, deadline_ms, trace_id=trace_id,
                                    finish_trace=finish_trace)
        else:
            res = self.streams.advance(session_id, image, deadline_ms,
                                       trace_id=trace_id,
                                       finish_trace=finish_trace)
        if finish_trace:
            res.pop("_trace", None)       # direct callers: already closed
            res.pop("_finished_at", None)
        return res


def serve_cli(args, config: RAFTConfig, load_params) -> int:
    """-m serve: build, warm, serve until SIGINT/SIGTERM, drain, exit 0."""
    import os
    import signal

    from .config import parse_buckets

    # flight recorder: default <out>/flightrec.jsonl; --flightrec '' turns
    # the auto-dump off (the /debug/traces endpoint still serves the ring)
    flightrec = getattr(args, "flightrec", None)
    if flightrec is None:
        flightrec = os.path.join(getattr(args, "out", None) or ".",
                                 "flightrec.jsonl")
    # metric history spill: default <out>/metrics_ts.jsonl (the flightrec
    # pattern); --history-path '' keeps the in-memory ring + endpoint but
    # skips the file
    history_path = getattr(args, "history_path", None)
    if history_path is None:
        history_path = os.path.join(getattr(args, "out", None) or ".",
                                    "metrics_ts.jsonl")
    try:
        sconfig = ServeConfig(
            buckets=parse_buckets(args.buckets),
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            default_deadline_ms=args.deadline_ms,
            host=args.host, port=args.port,
            dp_devices=args.serve_dp or 1,
            warmup=not args.no_warmup,
            iters_policy=getattr(args, "iters_policy", None),
            trace_sample=getattr(args, "trace_sample", 1.0),
            slo_pair_ms=getattr(args, "slo_pair_ms", 1000.0),
            slo_stream_ms=getattr(args, "slo_stream_ms", 500.0),
            flightrec_path=flightrec or None,
            # argparse owns the defaults; `or`-style fallbacks would
            # silently turn an (invalid) explicit 0 into the default
            # instead of letting ServeConfig raise on it
            max_sessions=getattr(args, "max_sessions", 64),
            session_ttl_s=getattr(args, "session_ttl_s", 300.0),
            ragged=getattr(args, "ragged", False),
            ragged_batch_pixels=getattr(args, "ragged_batch_pixels", 0),
            engine_cache_dir=getattr(args, "engine_cache_dir", None),
            history_interval_s=getattr(args, "history_interval_s", 1.0),
            history_window=getattr(args, "history_window", 600),
            history_path=history_path or None,
            anomaly=not getattr(args, "no_anomaly", False),
            anomaly_window_s=getattr(args, "anomaly_window_s", 15.0),
            anomaly_baseline_s=getattr(args, "anomaly_baseline_s", 60.0),
            # chaos drills: the CLI flag wins, the env var arms CI/ops.
            # breaker knobs use None-checks, not `or`: --breaker-window 0
            # is the documented breaker-off switch and must survive
            chaos=(getattr(args, "chaos", None)
                   or os.environ.get("RAFT_TPU_CHAOS") or None),
            **{k: v for k, v in {
                "breaker_window": getattr(args, "breaker_window", None),
                "breaker_threshold": getattr(args, "breaker_threshold",
                                             None),
                "breaker_cooldown_s": getattr(args, "breaker_cooldown_s",
                                              None),
            }.items() if v is not None})
    except ValueError as e:
        print(f"ERROR: {e}")
        return 2
    params = load_params(args, config)
    server = FlowServer(config, params, sconfig, iters=args.iters,
                        verbose=True,
                        trace_dir=getattr(args, "trace", None),
                        trace_steps=getattr(args, "trace_steps", None) or 4)
    out = getattr(args, "out", None)
    if out:
        server.profile_dir = os.path.join(out, "profiles")
    t0 = time.monotonic()
    server.start()
    print(f"[serve] listening on {server.url}  "
          f"buckets={[f'{h}x{w}' for h, w in sconfig.buckets]}  "
          f"max_batch={sconfig.max_batch}  "
          f"batch_steps={list(sconfig.batch_steps)}  "
          f"max_wait={sconfig.max_wait_ms}ms  "
          f"queue_depth={sconfig.queue_depth}  "
          f"iters_policy={server.engine.iters_policy}  "
          f"({time.monotonic() - t0:.1f}s to ready)")
    if sconfig.ragged:
        mh, mw = sconfig.max_box
        print(f"[serve] ragged: ONE executable per (kind, batch-step) at "
              f"the {mh}x{mw} arena serves every declared bucket  "
              f"batch_pixels="
              f"{sconfig.ragged_batch_pixels or 'unbounded'}")
    if server.streams is not None:
        print(f"[serve] streaming: max_sessions={sconfig.max_sessions}  "
              f"session_ttl={sconfig.session_ttl_s:.0f}s  "
              f"quant={config.quant}  "
              f"POST {server.url}/v1/stream")
    if server.engine_cache is not None:
        st = server.engine_cache.stats
        print(f"[serve] engine cache: dir={server.engine_cache.dir}  "
              f"loaded={st.hits}  compiled={st.misses}  "
              f"(warmup {server.engine.warmup_seconds:.1f}s)")
    if server.faults is not None:
        print(f"[serve] CHAOS ARMED: {sconfig.chaos} "
              f"(fault injection live — drills only)")
    if sconfig.trace_sample > 0:
        print(f"[serve] tracing: sample={sconfig.trace_sample:g}  "
              f"slo pair={sconfig.slo_pair_ms:.0f}ms "
              f"stream={sconfig.slo_stream_ms:.0f}ms  "
              f"flightrec={sconfig.flightrec_path or '(endpoint only)'}  "
              f"GET {server.url}/debug/traces")
    if server.history is not None:
        sentinels = ("armed" if server.anomaly is not None else "off")
        print(f"[serve] history: interval={sconfig.history_interval_s:g}s "
              f"window={sconfig.history_window}  sentinels={sentinels}  "
              f"spill={sconfig.history_path or '(ring only)'}  "
              f"GET {server.url}/debug/history   "
              f"POST {server.url}/debug/profile?ms=500")
    print(f"[serve] POST {server.url}/v1/flow   "
          f"GET {server.url}/healthz   GET {server.url}/metrics")

    def _stop(signum, frame):
        print(f"\n[serve] signal {signum}: draining "
              f"({len(server.queue)} queued)...")
        threading.Thread(target=server.stop, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    server.wait()
    b = server.batcher
    print(f"[serve] drained and stopped  served={b.served} "
          f"batches={b.batches} timed_out={b.timed_out}")
    return 0
