"""Deterministic fault injection for the serving stack (chaos harness).

Production serving fails in ways a clean test run never exercises: the
device raises mid-batch, a kernel stalls, an executable emits NaNs, a
cached session map goes bad, the batcher daemon dies to a stray bug.
"TensorFlow: a system for large-scale ML" (PAPERS.md) makes the case that
fault tolerance must be a designed-in axis of an ML system — which first
requires a way to *produce* the faults on demand.  This module is that
surface: a seeded, rate-configured injector armed via ``--chaos SPEC`` /
``RAFT_TPU_CHAOS``, with **zero overhead when off** (the server carries
``faults=None`` and every hook site is a single ``is not None`` check).

Spec grammar — comma-separated ``key=value`` pairs::

    seed=11,engine_error=0.05,latency=0.02,latency_ms=150,nan=0.03,
    session=0.05,kill=0.01

Arms (each a per-call firing rate in [0, 1]):

* ``engine_error`` — an engine device call raises :class:`FaultInjected`
  (exercises retry, poisoned-batch bisection, the circuit breaker).
* ``latency``      — an engine call sleeps ``latency_ms`` first
  (exercises deadlines and queue aging).
* ``nan``          — one row of a flow output is overwritten with NaN
  (exercises the non-finite output sentinel).
* ``session``      — a stream step's cached feature map is poisoned with
  NaN device-side (exercises the degrade-to-cold-restart path).
* ``kill``         — the batcher loop raises :class:`BatcherKilled`
  (exercises the supervisor: fail in-flight, restart, degraded healthz).

Every fire is deterministic given (seed, call order): each arm draws from
its own seeded RandomState, so a drill replays.  Fires are counted in
``raft_fault_injected_total{arm=}`` and appended to the active run log.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..lint.concurrency import guarded_by
from ..telemetry.log import get_logger
from ..telemetry.spans import current_trace_ids
from ..telemetry.watchdogs import watched_lock

_log = get_logger("serve")

ARMS = ("engine_error", "latency", "nan", "session", "kill")


class FaultInjected(RuntimeError):
    """An injected engine fault (chaos arm ``engine_error``)."""


class BatcherKilled(RuntimeError):
    """An injected batcher-thread death (chaos arm ``kill``)."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """Parsed ``--chaos`` spec: per-arm rates + the shared knobs."""

    seed: int = 0
    engine_error: float = 0.0
    latency: float = 0.0
    latency_ms: float = 100.0
    nan: float = 0.0
    session: float = 0.0
    kill: float = 0.0

    @property
    def armed(self) -> bool:
        return any(getattr(self, a) > 0 for a in ARMS)


def parse_chaos_spec(spec: str) -> ChaosSpec:
    """Parse ``"seed=11,engine_error=0.05,..."``; raises ValueError on an
    unknown key, a malformed pair, or a rate outside [0, 1]."""
    fields = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad chaos entry {part!r}: expected key=value")
        key, _, val = part.partition("=")
        key = key.strip()
        try:
            if key == "seed":
                fields[key] = int(val)
            elif key == "latency_ms":
                fields[key] = float(val)
                if fields[key] < 0:
                    raise ValueError
            elif key in ARMS:
                fields[key] = float(val)
                if not 0.0 <= fields[key] <= 1.0:
                    raise ValueError
            else:
                raise KeyError(key)
        except KeyError:
            raise ValueError(
                f"unknown chaos arm {key!r}; arms: {', '.join(ARMS)} "
                f"(+ seed, latency_ms)")
        except ValueError:
            raise ValueError(
                f"bad chaos value {part!r}: rates must be floats in [0, 1], "
                f"seed an int, latency_ms a non-negative float")
    return ChaosSpec(**fields)


def _arm_seed(seed: int, arm: str) -> int:
    # distinct, stable stream per arm: the same spec replays the same fault
    # schedule regardless of which other arms are configured
    return (seed * 1_000_003 + sum(ord(c) for c in arm) * 7919) % (2 ** 31)


class FaultInjector:
    """The armed injector one FlowServer carries.  All hook sites are
    driven by :meth:`roll` — deterministic per (seed, arm, call index) —
    so a drill with a pinned seed replays its fault schedule.

    Thread model: ``roll`` takes a lock (fires happen on the batcher
    thread and, for stream arms, nowhere else — but tests poke from
    anywhere).  ``disarm()`` mutes every rate-driven arm, which is how a
    drill ends its storm without tearing the server down; ``force()``
    queues explicit outcomes for deterministic tests and is honored even
    while disarmed.
    """

    _forced = guarded_by("_lock")
    _armed = guarded_by("_lock")
    injected = guarded_by("_lock")

    def __init__(self, spec: ChaosSpec, counter=None, run_log=None):
        self.spec = spec
        self.counter = counter            # raft_fault_injected_total{arm=}
        self.run_log = run_log            # telemetry.events.RunLog or None
        self._lock = watched_lock("FaultInjector._lock")
        self._rng = {arm: np.random.RandomState(_arm_seed(spec.seed, arm))
                     for arm in ARMS}
        self._row_rng = np.random.RandomState(_arm_seed(spec.seed, "row"))
        self._forced: Dict[str, deque] = {}
        self._armed = True
        self.injected: Dict[str, int] = {arm: 0 for arm in ARMS}

    # -- control (drills + tests) -----------------------------------------

    def disarm(self) -> None:
        """End the storm: every rate-driven arm stops firing (forced
        outcomes still drain — they are explicit test instructions)."""
        with self._lock:
            self._armed = False

    def rearm(self) -> None:
        with self._lock:
            self._armed = True

    def force(self, arm: str, outcomes) -> None:
        """Queue explicit roll outcomes for ``arm`` (1/True fires) —
        consumed before the seeded rng, for deterministic tests."""
        if arm not in ARMS:
            raise ValueError(f"unknown arm {arm!r}")
        with self._lock:
            self._forced.setdefault(arm, deque()).extend(
                bool(o) for o in outcomes)

    def injected_total(self) -> int:
        with self._lock:
            return sum(self.injected.values())

    # -- the roll ----------------------------------------------------------

    def roll(self, arm: str) -> bool:
        with self._lock:
            forced = self._forced.get(arm)
            if forced:
                hit = forced.popleft()
            elif not self._armed:
                return False
            else:
                rate = getattr(self.spec, arm)
                if rate <= 0.0:
                    return False
                hit = bool(self._rng[arm].random_sample() < rate)
            if hit:
                self.injected[arm] += 1
        if hit:
            if self.counter is not None:
                self.counter.labels(arm).inc()
            if self.run_log is not None:
                # the batch's trace ids ride along (telemetry/spans.py
                # ambient), so a drill's fault_injected events join to
                # the request traces they poisoned
                ids = current_trace_ids()
                self.run_log.event("fault_injected", arm=arm,
                                   trace_ids=list(ids) if ids else None)
            _log.warning(f"chaos: injecting fault arm={arm}")
        return hit

    # -- hook sites --------------------------------------------------------

    def pre_engine_call(self) -> None:
        """Engine-call prologue: latency spike, then injected exception."""
        if self.roll("latency"):
            time.sleep(self.spec.latency_ms / 1000.0)
        if self.roll("engine_error"):
            raise FaultInjected("injected engine fault "
                                "(chaos arm engine_error)")

    def corrupt_rows(self, flow: np.ndarray) -> np.ndarray:
        """NaN-poison one (deterministically chosen) row of a flow output
        when the ``nan`` arm fires; returns the input untouched otherwise."""
        if not self.roll("nan"):
            return flow
        flow = np.array(flow, copy=True)
        row = int(self._row_rng.randint(flow.shape[0]))
        flow[row] = np.nan
        return flow

    def corrupt_session(self, session, engine) -> None:
        """Poison a stream session's cached device feature map with NaN
        when the ``session`` arm fires — slot-pool form: the session's
        fmap ROW in the pool buffer is NaN'd in place (the engine's
        warmed ``spoison`` executable), so the poison rides the batched
        gather into the correlation volume and the flow output, which
        the non-finite sentinel must then catch and degrade that row to
        a cold restart."""
        if session.slot is None:
            return
        if self.roll("session"):
            engine.poison_slot(session.bucket, session.slot)

    def maybe_kill(self) -> None:
        """Batcher-loop hook: raise :class:`BatcherKilled` when the
        ``kill`` arm fires (the supervisor drill)."""
        if self.roll("kill"):
            raise BatcherKilled("injected batcher-thread death "
                                "(chaos arm kill)")


def make_injector(spec: Optional[str], counter=None,
                  run_log=None) -> Optional[FaultInjector]:
    """``--chaos``/env spec string -> injector, or None when the spec is
    empty/absent (the zero-overhead off state: call sites never even
    branch per arm).  An explicit spec builds the injector even with
    all-zero rates — tests drive those via ``force()``."""
    if not spec:
        return None
    return FaultInjector(parse_chaos_spec(spec), counter=counter,
                         run_log=run_log)
