"""AOT executable cache: serialize warmed executables to disk, load on boot.

PR 14's fleet made cold start the dominant cost of elasticity: every
autoscale-up, chaos respawn and rolling hot-swap pays the full
(kind x bucket x batch-step x policy) compile storm (~tens of seconds per
replica).  The compiled executables are pure functions of the config and
the device — so a replica that already paid the storm can export them, and
every later replica on the same (config, device kind, jax version) loads
instead of compiling.  With a warm cache directory a fresh replica serves
its first 200 with ZERO XLA compiles (RecompileWatch-verified — loading a
serialized executable fires no backend_compile_duration event).

Mechanism: ``jax.experimental.serialize_executable`` —
``serialize(compiled) -> (payload, in_tree, out_tree)`` round-trips a
``jax.stages.Compiled`` bit-identically through
``deserialize_and_load``.  (``jax.export`` is NOT suitable here: it
serializes StableHLO, which still compiles on load.)

Layout (SERVING.md "Cold start & cache")::

    <root>/<config_hash>-<device_kind>-<jax_version>/
        manifest.json          identity + warmup-grid signature
        pair-432x1024-b4-<policyhash>.bin    one pickle per engine key

Invalidation is whole-directory: the manifest's identity fields
(config_hash / device_kind / jax_version / jaxlib_version) must ALL match
the running process or the directory is treated cold and warmup falls back
to compiling — a stale cache can cost time, never correctness.  A corrupt
or unreadable entry is skipped with a warning (load counted, miss
counted), again falling back to compile.
"""

from __future__ import annotations

import dataclasses
import json
import hashlib
import logging
import os
import pickle
import re
import time
from pathlib import Path
from typing import Optional

_log = logging.getLogger("raft_tpu.serving.aot_cache")

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"

# The engine's executable-cache key, in order.  raftlint B5 checks this
# literal stays arity-synced with the tuples lint/budget.enumerate_warmup_grid
# emits — a key-schema drift between the compiler and the cache would
# silently mis-key every entry.
KEY_FIELDS = ("kind", "h", "w", "b", "policy")


def _slug(text: str) -> str:
    """Filesystem-safe token (device kinds like 'TPU v4' have spaces)."""
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", str(text)).strip("_") or "unknown"


def cache_identity(config) -> dict:
    """The (config, toolchain, device) identity a cache directory is valid
    for.  Every field must match exactly at load time."""
    import jax
    from ..telemetry.events import config_hash
    return {
        "config_hash": config_hash(config),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        "jaxlib_version": getattr(__import__("jaxlib"), "__version__",
                                  jax.__version__),
    }


def key_filename(key) -> str:
    """Deterministic per-key entry name: ``pair-432x1024-b4-<8hex>.bin``.

    The iters policy is free-form text ('converge:0.05:3'); hash it so the
    name stays filesystem-safe while distinct policies never collide.
    """
    kind, h, w, b, policy = key
    phash = hashlib.sha256(repr(policy).encode()).hexdigest()[:8]
    return f"{_slug(kind)}-{int(h)}x{int(w)}-b{int(b)}-{phash}.bin"


@dataclasses.dataclass
class CacheStats:
    """Counters mirrored to /metrics and /healthz.

    ``loads``  = deserialize attempts (file existed, we tried);
    ``hits``   = keys served from the cache;
    ``misses`` = keys that fell back to compile (absent, corrupt, or the
                 whole directory failed identity validation);
    ``saves``  = executables exported this process.
    """
    hits: int = 0
    misses: int = 0
    loads: int = 0
    saves: int = 0
    load_seconds: list = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "loads": self.loads, "saves": self.saves}


class EngineCache:
    """Disk cache of serialized engine executables for one config+device.

    Not thread-safe by design: the engine serializes warmup and export
    under its own lock.  Safe across processes for the fleet's shared-dir
    usage: entries are written via atomic rename, and two replicas racing
    to write the same key produce identical payloads.
    """

    def __init__(self, root, config):
        self.root = Path(root)
        self.identity = cache_identity(config)
        sub = (f"{self.identity['config_hash']}-"
               f"{_slug(self.identity['device_kind'])}-"
               f"{_slug(self.identity['jax_version'])}")
        self.dir = self.root / sub
        self.stats = CacheStats()
        self._valid: Optional[bool] = None   # manifest validation memo

    # -- identity / manifest ------------------------------------------------

    def validate(self) -> bool:
        """True when the directory's manifest matches this process's
        identity exactly.  Memoized; a missing manifest (fresh dir) is
        INVALID for loading but fine for saving — save() populates it."""
        if self._valid is None:
            self._valid = self._validate_once()
        return self._valid

    def _validate_once(self) -> bool:
        path = self.dir / MANIFEST_NAME
        try:
            manifest = json.loads(path.read_text())
        except FileNotFoundError:
            return False
        except Exception as e:                      # corrupt manifest
            _log.warning(f"engine cache: unreadable manifest {path}: {e}; "
                         f"treating directory as cold")
            return False
        if manifest.get("version") != MANIFEST_VERSION:
            _log.warning(f"engine cache: manifest version "
                         f"{manifest.get('version')!r} != {MANIFEST_VERSION}; "
                         f"treating directory as cold")
            return False
        for field, want in self.identity.items():
            got = manifest.get(field)
            if got != want:
                _log.warning(f"engine cache: stale {field} "
                             f"(cache {got!r} != process {want!r}); "
                             f"treating directory as cold")
                return False
        return True

    def write_manifest(self, grid) -> None:
        """Stamp the directory with identity + the warmup-grid signature
        (lint/budget.enumerate_warmup_grid output) — the authoritative
        list of keys a warm directory is expected to hold."""
        self.dir.mkdir(parents=True, exist_ok=True)
        manifest = {
            "version": MANIFEST_VERSION,
            **self.identity,
            "key_fields": list(KEY_FIELDS),
            "keys": [list(k) for k in grid],
            "entries": [key_filename(k) for k in grid],
            "created_unix": time.time(),
        }
        tmp = self.dir / (MANIFEST_NAME + ".tmp")
        tmp.write_text(json.dumps(manifest, indent=2, default=str))
        os.replace(tmp, self.dir / MANIFEST_NAME)
        self._valid = True

    def manifest(self) -> Optional[dict]:
        try:
            return json.loads((self.dir / MANIFEST_NAME).read_text())
        except Exception:
            return None

    # -- load / save --------------------------------------------------------

    def load(self, key):
        """Deserialize the executable for ``key``, or None (caller
        compiles).  Every None is counted as a miss; a file we attempted
        counts as a load; a success counts as a hit."""
        if not self.validate():
            self.stats.misses += 1
            return None
        path = self.dir / key_filename(key)
        if not path.exists():
            self.stats.misses += 1
            return None
        self.stats.loads += 1
        t0 = time.monotonic()
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            from jax.experimental import serialize_executable as _se
            ex = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            _log.warning(f"engine cache: corrupt entry {path.name} "
                         f"({type(e).__name__}: {e}); recompiling")
            self.stats.misses += 1
            return None
        self.stats.load_seconds.append(time.monotonic() - t0)
        self.stats.hits += 1
        return ex

    def save(self, key, compiled) -> bool:
        """Export a ``jax.stages.Compiled`` under ``key`` (atomic rename;
        idempotent — an existing entry is left alone).  Returns True when
        an entry exists on disk afterwards."""
        path = self.dir / key_filename(key)
        if path.exists():
            return True
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(f".tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                pickle.dump((payload, in_tree, out_tree), f)
            os.replace(tmp, path)
        except Exception as e:
            _log.warning(f"engine cache: could not export {key}: "
                         f"{type(e).__name__}: {e}")
            return False
        self.stats.saves += 1
        return True
