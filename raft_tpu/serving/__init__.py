"""Async micro-batching inference service (see SERVING.md).

The long-lived counterpart of the one-shot CLI modes: a bounded admission
queue with deadlines and 429 load shedding, a dynamic micro-batcher that
coalesces requests into pre-declared (resolution-bucket x batch-step)
shapes, a warm AOT-compiled engine cache (no recompiles after warmup),
stdlib Prometheus-text observability over ``http.server``, and a
sessionful streaming-video path (``/v1/stream``: cross-frame feature
reuse + warm-started early exit, session.py/stream.py).
"""

from .batcher import (BatcherCrashed, MicroBatcher, NonFiniteOutput,
                      PoisonedRequest)
from .breaker import BreakerOpen, CircuitBreaker
from .config import ServeConfig, default_batch_steps, parse_buckets
from .engine import InferenceEngine
from .faults import (BatcherKilled, ChaosSpec, FaultInjected, FaultInjector,
                     make_injector, parse_chaos_spec)
from .metrics import (Counter, Gauge, Histogram, Registry,
                      make_serving_metrics, make_stream_metrics)
from .queue import (DeadlineExceeded, Draining, QueueFull, RejectedError,
                    Request, RequestQueue)
from .server import BatcherSupervisor, FlowServer, serve_cli
from .session import Session, SessionStore, SlotPool
from .stream import (SessionBusy, StreamCoordinator, StreamRequest,
                     UnknownSession)
