from .weights import (assert_tree_shapes_match, from_reference_npz,
                      from_torch_state_dict, load_checkpoint_auto,
                      load_params_npz, save_params_npz, swap_rgb_bgr,
                      to_reference_npz, to_state_dict)
