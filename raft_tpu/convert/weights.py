"""Checkpoint conversion: official PyTorch ``.pth`` / reference ``.npz``
-> raft-tpu parameter pytrees.

The reference's checkpoint format contract is "npz keys = TF variable names
chosen to mirror the PyTorch state_dict" (reference infer_raft.py:77,
readme.md:28; SURVEY.md §3.4).  Our pytree keys already mirror the PyTorch
path segments, so conversion is a pure leaf-name + layout map:

  torch 'fnet.layer1.0.conv1.weight'  [O,I,kH,kW] -> ['fnet']['layer1']['0']['conv1']['w']  [kH,kW,I,O]
  torch 'cnet.norm1.weight'                       -> ['cnet']['norm1']['gamma']
  tensorpack 'fnet/layer1/0/conv1/W'  [kH,kW,I,O] -> same leaf, no transpose

Channel order: the official weights were trained on RGB input; the reference
feeds BGR (reference RAFT.py:13).  ``swap_input_channels=True`` permutes the
first conv's input channels of fnet and cnet so the converted model accepts
BGR directly (the CLI does this for torch checkpoints unless --rgb is given).
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

_TORCH_NORM_LEAVES = {
    "weight": "gamma", "bias": "beta",
    "running_mean": "mean", "running_var": "var",
}
_TP_LEAVES = {
    "W": "w", "b": "b", "gamma": "gamma", "beta": "beta",
    "mean/EMA": "mean", "variance/EMA": "var",
}


def _set_path(tree: dict, parts, leaf_name: str, value: np.ndarray) -> None:
    node = tree
    for p in parts:
        node = node.setdefault(p, {})
    node[leaf_name] = value


def from_torch_state_dict(state_dict: Mapping[str, np.ndarray],
                          swap_input_channels: bool = False,
                          strict: bool = True) -> Dict[str, dict]:
    """Convert a torch state_dict (tensors or ndarrays) to a params pytree.

    Handles the official RAFT naming, with or without the DataParallel
    ``module.`` prefix; conv kernels are transposed OIHW -> HWIO.
    """
    params: Dict[str, dict] = {}
    skipped = []
    for name, value in state_dict.items():
        arr = np.asarray(getattr(value, "numpy", lambda: value)())
        if name.startswith("module."):
            name = name[len("module."):]
        parts = name.split(".")
        leaf = parts[-1]
        if leaf == "num_batches_tracked":
            continue
        if arr.ndim == 4 and leaf == "weight":            # conv kernel
            _set_path(params, parts[:-1], "w", arr.transpose(2, 3, 1, 0))
        elif leaf == "weight" and arr.ndim == 1:          # norm gamma
            _set_path(params, parts[:-1], "gamma", arr)
        elif leaf == "bias" and arr.ndim == 1:
            # conv bias vs norm beta: decide by sibling weight rank later;
            # record as 'b' and fix up in _fix_biases
            _set_path(params, parts[:-1], "b", arr)
        elif leaf in _TORCH_NORM_LEAVES:
            _set_path(params, parts[:-1], _TORCH_NORM_LEAVES[leaf], arr)
        else:
            skipped.append(name)
    if skipped and strict:
        raise ValueError(f"unrecognized state_dict entries: {skipped}")
    _fix_biases(params)
    _drop_aliased_norms(params)
    if swap_input_channels:
        swap_rgb_bgr(params)
    return params


def _fix_biases(node: dict) -> None:
    """A module with 'gamma' is a norm layer: its 'b' is really 'beta'."""
    if "gamma" in node and "b" in node and "w" not in node:
        node["beta"] = node.pop("b")
    for v in node.values():
        if isinstance(v, dict):
            _fix_biases(v)


def _drop_aliased_norms(node: dict) -> None:
    """Official checkpoints register the strided-block shortcut norm twice:
    as an attribute (``norm3`` on ResidualBlock, ``norm4`` on
    BottleneckBlock) AND inside the downsample Sequential (``downsample.1``)
    — the same tensors under two names.  Keep the canonical ``downsample.1``
    copy; drop the attribute alias after checking the two agree (a mismatch
    would mean the checkpoint is not official-RAFT shaped)."""
    ds = node.get("downsample")
    if isinstance(ds, dict) and isinstance(ds.get("1"), dict):
        alias = "norm4" if "conv3" in node else "norm3"
        dup = node.get(alias)
        if isinstance(dup, dict):
            canon = ds["1"]
            for k, v in dup.items():
                if k not in canon or not np.array_equal(np.asarray(v),
                                                        np.asarray(canon[k])):
                    raise ValueError(
                        f"shortcut-norm alias '{alias}' disagrees with "
                        f"downsample.1 on leaf {k!r}")
            del node[alias]
    for v in node.values():
        if isinstance(v, dict):
            _drop_aliased_norms(v)


def swap_rgb_bgr(params: Dict[str, dict]) -> None:
    """In-place: permute the input channels of the stem convs (fnet/cnet
    conv1) so a model trained on RGB accepts BGR (or vice versa)."""
    for enc in ("fnet", "cnet"):
        w = params[enc]["conv1"]["w"]                     # [kH, kW, 3, C]
        params[enc]["conv1"]["w"] = np.ascontiguousarray(w[:, :, ::-1, :])


def from_reference_npz(path_or_dict, strict: bool = True) -> Dict[str, dict]:
    """Convert a reference-style ``.npz`` (tensorpack variable names, HWIO
    kernels) to a params pytree (reference weight-load path, SURVEY.md §3.4)."""
    if isinstance(path_or_dict, (str, bytes)) or hasattr(path_or_dict, "__fspath__"):
        data = dict(np.load(path_or_dict))
    else:
        data = dict(path_or_dict)
    params: Dict[str, dict] = {}
    skipped = []
    for name, arr in data.items():
        name = name.removesuffix(":0")
        parts = name.split("/")
        # leaf may be 'W', 'b', 'gamma', 'beta', 'mean/EMA', 'variance/EMA'
        if len(parts) >= 2 and parts[-1] == "EMA":
            leaf_key = "/".join(parts[-2:])
            parts = parts[:-2]
        else:
            leaf_key = parts[-1]
            parts = parts[:-1]
        if leaf_key not in _TP_LEAVES:
            skipped.append(name)
            continue
        _set_path(params, parts, _TP_LEAVES[leaf_key], np.asarray(arr))
    if skipped and strict:
        raise ValueError(f"unrecognized npz entries: {skipped}")
    return params


# derived inverse of _TP_LEAVES so import/export cannot silently diverge
_TP_LEAVES_INV = {v: k for k, v in _TP_LEAVES.items()}


def _flatten_tree(node: Mapping, prefix=()):
    """Yield (path_parts, leaf_key, value) for every leaf of a params tree."""
    for k, v in node.items():
        if isinstance(v, dict):
            yield from _flatten_tree(v, prefix + (k,))
        else:
            yield prefix, k, v


def to_reference_npz(params: Dict[str, dict], path=None) -> Dict[str, np.ndarray]:
    """Export a params pytree in the reference's checkpoint naming (SURVEY.md
    §3.4: tensorpack variable names — '/'-separated module path, leaves
    ``W``/``b``/``gamma``/``beta``/``mean/EMA``/``variance/EMA``, HWIO
    kernels) — the exact inverse of :func:`from_reference_npz`, so interop
    with a reference-consuming pipeline is proven in BOTH directions
    (reference infer_raft.py:77 loads exactly this shape of npz).  Returns
    the flat dict; also writes it to ``path`` when given."""
    flat: Dict[str, np.ndarray] = {}
    for parts, k, v in _flatten_tree(params):
        if k not in _TP_LEAVES_INV:
            raise ValueError(f"unknown leaf {k!r} at {'/'.join(parts)}")
        flat["/".join(parts + (_TP_LEAVES_INV[k],))] = np.asarray(v)
    if path is not None:
        np.savez(path, **flat)
    return flat


def to_state_dict(params: Dict[str, dict], torch_layout: bool = True) -> Dict[str, np.ndarray]:
    """Flatten a params pytree back to a torch-style state_dict (for export
    and round-trip testing)."""
    out: Dict[str, np.ndarray] = {}

    def walk(node, prefix):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(v, prefix + [k])
            else:
                arr = np.asarray(v)
                if k == "w":
                    name, val = "weight", arr.transpose(3, 2, 0, 1) if torch_layout else arr
                elif k == "b":
                    name, val = "bias", arr
                elif k == "gamma":
                    name, val = "weight", arr
                elif k == "beta":
                    name, val = "bias", arr
                elif k == "mean":
                    name, val = "running_mean", arr
                elif k == "var":
                    name, val = "running_var", arr
                else:
                    raise ValueError(f"unknown leaf {k}")
                out[".".join(prefix + [name])] = val

    walk(params, [])
    return out


def save_params_npz(params: Dict[str, dict], path) -> None:
    """Save a params pytree as a flat npz ('/'-joined keys, HWIO layout) —
    the native raft-tpu single-file checkpoint format."""
    flat = {"/".join(parts + (k,)): np.asarray(v)
            for parts, k, v in _flatten_tree(params)}
    np.savez(path, **flat)


def load_params_npz(path) -> Dict[str, dict]:
    """Inverse of save_params_npz."""
    params: Dict[str, dict] = {}
    with np.load(path) as data:
        for name in data.files:
            parts = name.split("/")
            _set_path(params, parts[:-1], parts[-1], data[name])
    return params


def detect_format(path) -> str:
    """'torch' (.pth/.pt or torch-named npz), 'tensorpack' (reference npz),
    'trainstate' (a training-loop checkpoint: full TrainState with path-named
    leaves), or 'native' (params-only raft-tpu npz)."""
    spath = str(path)
    if spath.endswith((".pth", ".pt")):
        return "torch"
    with np.load(spath) as data:
        names = list(data.files)
    if "step" in names and any(n.startswith("params/") for n in names):
        return "trainstate"
    if names and all(n.startswith("leaf_") for n in names):
        raise ValueError(
            f"{path} is a positional (pre-path-naming) TrainState "
            f"checkpoint; it can only be restored by the training loop "
            f"(resume), or re-saved by it in the current format")
    if any("." in n and "/" not in n for n in names):
        return "torch"
    leaves = {n.split("/")[-1] for n in names}
    if "W" in leaves or "EMA" in leaves:
        return "tensorpack"
    return "native"


def from_train_checkpoint(path) -> Dict[str, dict]:
    """Extract inference-ready full params (trainable + BN running stats)
    from a training-loop checkpoint (training/checkpoint.py path-named
    TrainState npz) — train then infer with the very file the loop wrote,
    the journey the reference never supported in either direction."""
    from ..training.state import merge_bn_state
    params: Dict[str, dict] = {}
    bn: Dict[str, dict] = {}
    with np.load(str(path)) as data:
        for name in data.files:
            parts = name.split("/")
            if parts[0] == "params" and len(parts) > 1:
                _set_path(params, parts[1:-1], parts[-1], data[name])
            elif parts[0] == "bn_state" and len(parts) > 1:
                _set_path(bn, parts[1:-1], parts[-1], data[name])
    if not params:
        raise ValueError(f"{path} contains no params/ leaves")
    return merge_bn_state(params, bn)


def load_checkpoint_auto(path) -> Dict[str, dict]:
    """Load any supported checkpoint: torch .pth, reference/tensorpack npz,
    native params npz, or a training-loop TrainState checkpoint.  Dispatch:
    .pth -> torch loader; npz with '.'-dotted torch names -> torch map; npz
    with W/'mean/EMA' leaves -> tensorpack map; npz with step + params/
    leaves -> TrainState params extraction; npz with w/gamma leaves ->
    native."""
    spath = str(path)
    fmt = detect_format(spath)
    if fmt == "trainstate":
        return from_train_checkpoint(spath)
    if fmt == "torch":
        if spath.endswith((".pth", ".pt")):
            import torch
            sd = torch.load(spath, map_location="cpu", weights_only=True)
            if isinstance(sd, dict) and "model" in sd and isinstance(sd["model"], dict):
                sd = sd["model"]
            return from_torch_state_dict(sd)
        with np.load(spath) as data:
            return from_torch_state_dict({n: data[n] for n in data.files})
    if fmt == "tensorpack":
        return from_reference_npz(spath)
    return load_params_npz(spath)


def assert_tree_shapes_match(converted: Dict[str, dict], expected: Dict[str, dict],
                             path: str = "") -> None:
    """Raise with a precise path if structures/shapes differ."""
    ek = set(expected.keys())
    ck = set(converted.keys())
    if ek != ck:
        raise ValueError(f"at {path or '<root>'}: keys differ; "
                         f"missing={sorted(ek - ck)} extra={sorted(ck - ek)}")
    for k in expected:
        e, c = expected[k], converted[k]
        if isinstance(e, dict):
            assert_tree_shapes_match(c, e, f"{path}{k}.")
        else:
            if tuple(np.shape(c)) != tuple(np.shape(e)):
                raise ValueError(f"at {path}{k}: shape {np.shape(c)} != {np.shape(e)}")
