"""Host-side classical-flow and image tools.

Covers the reference's side utilities (flow_utils.py:123-274): sharpening /
contrast augmentation, the DIS-optical-flow + guided-filter baseline, static-
region masking, and forward->backward flow reversal by splatting.  The
reversal is re-designed: the reference runs a pure-Python double loop over
every pixel plus a per-empty-pixel 4-direction scan (flow_utils.py:166-274);
here both passes are vectorized numpy (scatter-add + directional index
propagation), identical semantics, orders of magnitude faster.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


def aug_img(im: np.ndarray, contrast: float = 1.5, bias: float = 0.0,
            usm_sigma: float = 5.0) -> np.ndarray:
    """Contrast stretch + unsharp-mask sharpening (reference flow_utils.py:123-135)."""
    import cv2
    im = np.uint8(np.clip(contrast * im + bias, 0, 255))
    blur = cv2.GaussianBlur(im, (0, 0), usm_sigma)
    return cv2.addWeighted(im, 1.5, blur, -0.5, 0)


def calc_flow(im0: np.ndarray, im1: np.ndarray, use_yuv: bool = False) -> np.ndarray:
    """Classical DIS optical-flow baseline with guided-filter post-processing
    (reference flow_utils.py:137-153).  Requires opencv-contrib's ximgproc for
    the guided filter; falls back to the raw DIS flow without it."""
    import cv2
    if use_yuv:
        g0 = cv2.cvtColor(im0, cv2.COLOR_BGR2YUV)[:, :, 0]
        g1 = cv2.cvtColor(im1, cv2.COLOR_BGR2YUV)[:, :, 0]
    else:
        g0 = cv2.cvtColor(im0, cv2.COLOR_BGR2GRAY)
        g1 = cv2.cvtColor(im1, cv2.COLOR_BGR2GRAY)
    inst = cv2.DISOpticalFlow_create(cv2.DISOPTICAL_FLOW_PRESET_MEDIUM)
    flow = inst.calc(g0, g1, None)
    try:
        return cv2.ximgproc.guidedFilter(im0, flow, radius=9, eps=2)
    except AttributeError:
        return flow


def set_static_flow(flow01: np.ndarray, im0: np.ndarray, bg: np.ndarray,
                    thresh: float = 5.0) -> np.ndarray:
    """Zero flow where im0 matches the static background plate
    (reference flow_utils.py:155-159)."""
    static = np.prod(np.abs(bg.astype(np.float64) - im0) < thresh,
                     axis=-1, keepdims=True)
    return np.where(static, 0.0, flow01)


def erode_mask(mask: np.ndarray, r: int = 5) -> np.ndarray:
    """Rectangular erosion (reference flow_utils.py:161-163)."""
    import cv2
    kernel = cv2.getStructuringElement(cv2.MORPH_RECT, (r, r))
    return cv2.erode(mask, kernel)


class ReversedFlow(NamedTuple):
    flow10: np.ndarray          # [H, W, 2] backward flow
    empty: np.ndarray           # uint8 [H, W] pixels with no projection
    conflict: np.ndarray        # uint8 [H, W] pixels hit more than once
    static_mask: np.ndarray     # [H, W, 1] static-region mask (or zeros)
    empty_before_fill: np.ndarray


def _nearest_fill(values: np.ndarray, empty: np.ndarray) -> np.ndarray:
    """For each empty pixel, average the nearest non-empty value looking
    up / down / left / right (the reference's fiil_ind semantics,
    flow_utils.py:229-262), vectorized via directional index propagation.

    All four scans read only the ORIGINAL non-empty pixels, as the reference
    does (it never marks filled pixels non-empty during the pass)."""
    h, w = empty.shape
    valid = ~empty.astype(bool)

    def propagate(along_cols: bool, reverse: bool) -> tuple[np.ndarray, np.ndarray]:
        """Nearest-valid index per pixel scanning each row (or column).
        Self is never valid (it's empty), so this is 'strictly before/after'."""
        v = valid.T if along_cols else valid
        n = v.shape[1]
        idx = np.broadcast_to(np.arange(n), v.shape)
        if reverse:
            v = v[:, ::-1]
        filled = np.maximum.accumulate(np.where(v, idx, -1), axis=1)
        if reverse:
            filled = np.where(filled[:, ::-1] >= 0, (n - 1) - filled[:, ::-1], -1)
        has = filled >= 0
        if along_cols:
            return filled.T, has.T
        return filled, has

    rows = np.arange(h)[:, None]
    cols = np.arange(w)[None, :]
    acc = np.zeros(values.shape, np.float64)
    cnt = np.zeros((h, w), np.float64)

    for along_cols in (False, True):
        for reverse in (False, True):
            filled, has = propagate(along_cols, reverse)
            if along_cols:   # up / down: nearest valid in the same column
                src = values[np.clip(filled, 0, h - 1), cols]
            else:            # left / right: nearest valid in the same row
                src = values[rows, np.clip(filled, 0, w - 1)]
            acc += np.where(has[..., None], src, 0.0)
            cnt += has

    # pixels with no valid neighbor in any direction stay 0 (acc is 0 there)
    out = values.copy()
    fill = empty.astype(bool)
    out[fill] = (acc / np.maximum(cnt, 1.0)[..., None])[fill]
    return out


def _splat_average(flow: np.ndarray, values: np.ndarray,
                   skip: Optional[np.ndarray] = None,
                   oob: str = "clip") -> tuple:
    """Scatter-average ``values`` at each pixel's rounded flow target
    (conflict averaging).  The one splat kernel shared by flow reversal and
    warm-start projection — their semantics differ only in the
    out-of-bounds policy:

    - ``oob="clip"``: exiting targets pin to the border (the reference
      reversal semantics, flow_utils.py:166-274).
    - ``oob="discard"``: exiting pixels are dropped, tested on the
      UNROUNDED target like the official warm-start's strict
      ``(x1 > 0) & (x1 < wd)`` mask — border cells then fill from in-frame
      hits instead of inheriting the exiting motion.

    Returns (averaged [H, W, C] float64, hit mask [H, W] bool,
    hit count [H, W] float64)."""
    h, w = flow.shape[:2]
    tx = flow[:, :, 0] + np.arange(w)
    ty = flow[:, :, 1] + np.arange(h)[:, None]
    if oob == "discard":
        keep = (tx > 0) & (tx < w) & (ty > 0) & (ty < h)
    elif oob == "clip":
        keep = np.ones((h, w), bool)
    else:
        raise ValueError(f"oob must be 'clip' or 'discard', got {oob!r}")
    if skip is not None:
        keep &= ~skip
    txi = np.clip(np.rint(tx), 0, w - 1).astype(np.int64)
    tyi = np.clip(np.rint(ty), 0, h - 1).astype(np.int64)
    flat_idx = (tyi * w + txi)[keep]
    acc = np.zeros((h * w, values.shape[-1]), np.float64)
    count = np.zeros(h * w, np.float64)
    np.add.at(acc, flat_idx, values[keep])
    np.add.at(count, flat_idx, 1.0)
    hit = count > 1e-7
    acc[hit] /= count[hit, None]
    return (acc.reshape(h, w, -1), hit.reshape(h, w),
            count.reshape(h, w))


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """Forward-project a flow field along itself: each source pixel carries
    its flow VALUE to its rounded target position (conflict averaging), and
    unhit pixels are filled from their nearest hit neighbor.

    This is the warm-start initializer of the official RAFT Sintel
    evaluation (frame t's low-res flow, projected forward, seeds frame
    t+1's recurrence).  The official code scatters through
    scipy.interpolate.griddata(nearest) after discarding pixels whose
    target leaves the frame; this is a vectorized splat (same discard
    policy) + a GLOBAL nearest fill via distance-transform labels — the
    same dense nearest-extrapolation semantics without the per-call
    Delaunay cost.  (The axis-only ``_nearest_fill`` used by flow reversal
    is not enough here: a uniform flow leaves whole corner regions with no
    hit in their row or column.)
    In/out: [H, W, 2] float32 (any resolution; RAFT uses the 1/8 grid)."""
    h, w = flow.shape[:2]
    f = flow.astype(np.float64)
    out, hit, _ = _splat_average(f, f, oob="discard")
    if not hit.any():
        return np.zeros_like(flow, dtype=np.float32)
    empty = np.uint8(~hit)
    if empty.any():
        import cv2
        # label of the nearest hit pixel for every pixel; OpenCV numbers the
        # zero pixels of `empty` (the hits) 1..N in row-major scan order
        _, labels = cv2.distanceTransformWithLabels(
            empty, cv2.DIST_L2, 3, labelType=cv2.DIST_LABEL_PIXEL)
        hit_rc = np.argwhere(empty == 0)
        nearest = hit_rc[labels - 1]                 # [H, W, 2] (row, col)
        fill = empty.astype(bool)
        out[fill] = out[nearest[fill][:, 0], nearest[fill][:, 1]]
    return out.astype(np.float32)


def reverse_flow(flow01: np.ndarray, bg: Optional[np.ndarray] = None,
                 im0: Optional[np.ndarray] = None, time_step: float = 1.0,
                 static_thresh: float = 10.0) -> ReversedFlow:
    """Forward flow -> backward flow by projecting each source pixel to its
    rounded target, accumulating -flow with conflict averaging, then filling
    holes with the nearest-neighbor average (reference flow_utils.py:166-274,
    FLOW_PROJECTION_ROUND=True path).  Static pixels (im0 == bg) are skipped."""
    h, w = flow01.shape[:2]
    flow = flow01.astype(np.float64) * time_step

    if bg is not None and im0 is not None:
        diff = np.abs(bg.astype(np.float64) - im0)
        static_mask = np.prod(diff < static_thresh, axis=-1, keepdims=True)
        skip = static_mask[:, :, 0].astype(bool)
    else:
        static_mask = np.zeros((h, w, 1))
        skip = np.zeros((h, w), bool)

    flow10, hit, count = _splat_average(flow, -flow, skip=skip, oob="clip")
    empty = np.uint8(~hit)
    empty_before_fill = empty.copy()

    flow10 = _nearest_fill(flow10, empty)
    return ReversedFlow(flow10.astype(np.float32), empty,
                        np.uint8(count > 1), static_mask, empty_before_fill)
