"""Host-side classical-flow and image tools.

Covers the reference's side utilities (flow_utils.py:123-274): sharpening /
contrast augmentation, the DIS-optical-flow + guided-filter baseline, static-
region masking, and forward->backward flow reversal by splatting.  The
reversal is re-designed: the reference runs a pure-Python double loop over
every pixel plus a per-empty-pixel 4-direction scan (flow_utils.py:166-274);
here both passes are vectorized numpy (scatter-add + directional index
propagation), identical semantics, orders of magnitude faster.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


def aug_img(im: np.ndarray, contrast: float = 1.5, bias: float = 0.0,
            usm_sigma: float = 5.0) -> np.ndarray:
    """Contrast stretch + unsharp-mask sharpening (reference flow_utils.py:123-135)."""
    import cv2
    im = np.uint8(np.clip(contrast * im + bias, 0, 255))
    blur = cv2.GaussianBlur(im, (0, 0), usm_sigma)
    return cv2.addWeighted(im, 1.5, blur, -0.5, 0)


def calc_flow(im0: np.ndarray, im1: np.ndarray, use_yuv: bool = False) -> np.ndarray:
    """Classical DIS optical-flow baseline with guided-filter post-processing
    (reference flow_utils.py:137-153).  Requires opencv-contrib's ximgproc for
    the guided filter; falls back to the raw DIS flow without it."""
    import cv2
    if use_yuv:
        g0 = cv2.cvtColor(im0, cv2.COLOR_BGR2YUV)[:, :, 0]
        g1 = cv2.cvtColor(im1, cv2.COLOR_BGR2YUV)[:, :, 0]
    else:
        g0 = cv2.cvtColor(im0, cv2.COLOR_BGR2GRAY)
        g1 = cv2.cvtColor(im1, cv2.COLOR_BGR2GRAY)
    inst = cv2.DISOpticalFlow_create(cv2.DISOPTICAL_FLOW_PRESET_MEDIUM)
    flow = inst.calc(g0, g1, None)
    try:
        return cv2.ximgproc.guidedFilter(im0, flow, radius=9, eps=2)
    except AttributeError:
        return flow


def set_static_flow(flow01: np.ndarray, im0: np.ndarray, bg: np.ndarray,
                    thresh: float = 5.0) -> np.ndarray:
    """Zero flow where im0 matches the static background plate
    (reference flow_utils.py:155-159)."""
    static = np.prod(np.abs(bg.astype(np.float64) - im0) < thresh,
                     axis=-1, keepdims=True)
    return np.where(static, 0.0, flow01)


def erode_mask(mask: np.ndarray, r: int = 5) -> np.ndarray:
    """Rectangular erosion (reference flow_utils.py:161-163)."""
    import cv2
    kernel = cv2.getStructuringElement(cv2.MORPH_RECT, (r, r))
    return cv2.erode(mask, kernel)


class ReversedFlow(NamedTuple):
    flow10: np.ndarray          # [H, W, 2] backward flow
    empty: np.ndarray           # uint8 [H, W] pixels with no projection
    conflict: np.ndarray        # uint8 [H, W] pixels hit more than once
    static_mask: np.ndarray     # [H, W, 1] static-region mask (or zeros)
    empty_before_fill: np.ndarray


def _nearest_fill(values: np.ndarray, empty: np.ndarray) -> np.ndarray:
    """For each empty pixel, average the nearest non-empty value looking
    up / down / left / right (the reference's fiil_ind semantics,
    flow_utils.py:229-262), vectorized via directional index propagation.

    All four scans read only the ORIGINAL non-empty pixels, as the reference
    does (it never marks filled pixels non-empty during the pass)."""
    h, w = empty.shape
    valid = ~empty.astype(bool)

    def propagate(along_cols: bool, reverse: bool) -> tuple[np.ndarray, np.ndarray]:
        """Nearest-valid index per pixel scanning each row (or column).
        Self is never valid (it's empty), so this is 'strictly before/after'."""
        v = valid.T if along_cols else valid
        n = v.shape[1]
        idx = np.broadcast_to(np.arange(n), v.shape)
        if reverse:
            v = v[:, ::-1]
        filled = np.maximum.accumulate(np.where(v, idx, -1), axis=1)
        if reverse:
            filled = np.where(filled[:, ::-1] >= 0, (n - 1) - filled[:, ::-1], -1)
        has = filled >= 0
        if along_cols:
            return filled.T, has.T
        return filled, has

    rows = np.arange(h)[:, None]
    cols = np.arange(w)[None, :]
    acc = np.zeros(values.shape, np.float64)
    cnt = np.zeros((h, w), np.float64)

    for along_cols in (False, True):
        for reverse in (False, True):
            filled, has = propagate(along_cols, reverse)
            if along_cols:   # up / down: nearest valid in the same column
                src = values[np.clip(filled, 0, h - 1), cols]
            else:            # left / right: nearest valid in the same row
                src = values[rows, np.clip(filled, 0, w - 1)]
            acc += np.where(has[..., None], src, 0.0)
            cnt += has

    # pixels with no valid neighbor in any direction stay 0 (acc is 0 there)
    out = values.copy()
    fill = empty.astype(bool)
    out[fill] = (acc / np.maximum(cnt, 1.0)[..., None])[fill]
    return out


def reverse_flow(flow01: np.ndarray, bg: Optional[np.ndarray] = None,
                 im0: Optional[np.ndarray] = None, time_step: float = 1.0,
                 static_thresh: float = 10.0) -> ReversedFlow:
    """Forward flow -> backward flow by projecting each source pixel to its
    rounded target, accumulating -flow with conflict averaging, then filling
    holes with the nearest-neighbor average (reference flow_utils.py:166-274,
    FLOW_PROJECTION_ROUND=True path).  Static pixels (im0 == bg) are skipped."""
    h, w = flow01.shape[:2]
    flow = flow01.astype(np.float64) * time_step

    if bg is not None and im0 is not None:
        diff = np.abs(bg.astype(np.float64) - im0)
        static_mask = np.prod(diff < static_thresh, axis=-1, keepdims=True)
        skip = static_mask[:, :, 0].astype(bool)
    else:
        static_mask = np.zeros((h, w, 1))
        skip = np.zeros((h, w), bool)

    tx = np.clip(np.rint(flow[:, :, 0] + np.arange(w)), 0, w - 1).astype(np.int64)
    ty = np.clip(np.rint(flow[:, :, 1] + np.arange(h)[:, None]), 0, h - 1).astype(np.int64)

    keep = ~skip
    flat_idx = (ty * w + tx)[keep]
    flow10 = np.zeros((h * w, 2), np.float64)
    count = np.zeros(h * w, np.float64)
    np.add.at(flow10, flat_idx, -flow[keep])
    np.add.at(count, flat_idx, 1.0)

    hit = count > 1e-7
    flow10[hit] /= count[hit, None]
    flow10 = flow10.reshape(h, w, 2)
    count = count.reshape(h, w)
    empty = np.uint8(~hit.reshape(h, w))
    empty_before_fill = empty.copy()

    flow10 = _nearest_fill(flow10, empty)
    return ReversedFlow(flow10.astype(np.float32), empty,
                        np.uint8(count > 1), static_mask, empty_before_fill)
