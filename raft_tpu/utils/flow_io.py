"""Flow-field file I/O: Middlebury .flo, PFM (FlyingThings3D), KITTI 16-bit
PNG, plus flow resizing.  Covers reference flow_utils.py:277-318 and extends
it with the formats the training datasets need (the reference had no
training, SURVEY.md §3.6).
"""

from __future__ import annotations

import re
from pathlib import Path

import numpy as np

_FLO_MAGIC = 202021.25  # 'PIEH' interpreted as float


def read_flo(path) -> np.ndarray:
    """Read a Middlebury .flo file -> [H, W, 2] float32."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, 1)[0]
        if magic != _FLO_MAGIC:
            raise ValueError(f"{path}: bad .flo magic {magic}")
        w = int(np.fromfile(f, np.int32, 1)[0])
        h = int(np.fromfile(f, np.int32, 1)[0])
        data = np.fromfile(f, np.float32, h * w * 2)
    return data.reshape(h, w, 2)


def write_flo(flow: np.ndarray, path) -> None:
    """Write [H, W, 2] flow as .flo."""
    assert flow.ndim == 3 and flow.shape[2] == 2, flow.shape
    with open(path, "wb") as f:
        np.float32(_FLO_MAGIC).tofile(f)
        np.array([flow.shape[1], flow.shape[0]], np.int32).tofile(f)
        flow.astype(np.float32).tofile(f)


# readFlow/writeFlow aliases matching the reference API surface
readFlow = read_flo
writeFlow = write_flo


def read_pfm(path) -> np.ndarray:
    """Read a PFM file (FlyingThings3D disparity/flow) -> float32 array."""
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        color = header == b"PF"
        if header not in (b"PF", b"Pf"):
            raise ValueError(f"{path}: not a PFM file")
        dims = re.match(rb"^(\d+)\s(\d+)\s$", f.readline())
        if not dims:
            raise ValueError(f"{path}: malformed PFM header")
        w, h = map(int, dims.groups())
        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        data = np.fromfile(f, endian + "f")
    shape = (h, w, 3) if color else (h, w)
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(arr: np.ndarray, path) -> None:
    """Write a PFM file (color 'PF' for [H, W, 3], grayscale 'Pf' for
    [H, W]); rows bottom-up, little-endian (scale header -1.0), per the
    Middlebury/FlyingThings3D spec — the exact inverse of read_pfm.
    (No scale parameter: samples are written as-is; a header scale other
    than +/-1 would require multiplying the data for spec-compliant
    readers, which no caller here needs.)"""
    arr = np.asarray(arr, np.float32)
    if arr.ndim == 3 and arr.shape[2] == 3:
        header = b"PF"
    elif arr.ndim == 2:
        header = b"Pf"
    else:
        raise ValueError(f"PFM holds [H,W] or [H,W,3], got {arr.shape}")
    with open(path, "wb") as f:
        f.write(header + b"\n")
        f.write(f"{arr.shape[1]} {arr.shape[0]}\n".encode())
        f.write(b"-1.0\n")                     # negative = little-endian
        np.flipud(arr).astype("<f4").tofile(f)


def read_kitti_flow(path) -> tuple[np.ndarray, np.ndarray]:
    """KITTI 16-bit PNG flow -> ([H, W, 2] flow, [H, W] valid mask)."""
    import cv2
    raw = cv2.imread(str(path), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise FileNotFoundError(path)
    raw = raw[:, :, ::-1].astype(np.float32)   # BGR -> RGB = (u, v, valid)
    flow = (raw[:, :, :2] - 2 ** 15) / 64.0
    valid = raw[:, :, 2] > 0.5
    return flow, valid


def write_kitti_flow(flow: np.ndarray, path, valid: np.ndarray | None = None) -> None:
    import cv2
    h, w = flow.shape[:2]
    out = np.ones((h, w, 3), np.uint16)
    if valid is not None:
        out[:, :, 2] = valid.astype(np.uint16)
    out[:, :, :2] = np.clip(flow * 64.0 + 2 ** 15, 0, 2 ** 16 - 1).astype(np.uint16)
    cv2.imwrite(str(path), out[:, :, ::-1])


def read_flow_any(path) -> np.ndarray:
    """Dispatch by extension (.flo / .pfm / .png)."""
    suffix = Path(path).suffix.lower()
    if suffix == ".flo":
        return read_flo(path)
    if suffix == ".pfm":
        return read_pfm(path)[:, :, :2]
    if suffix == ".png":
        return read_kitti_flow(path)[0]
    raise ValueError(f"unknown flow format: {path}")


def resize_flow(flow: np.ndarray, new_w: int, new_h: int) -> np.ndarray:
    """Resize [H, W, 2] flow, rescaling u, v by the size ratio
    (reference flow_utils.py:277-284)."""
    import cv2
    h, w = flow.shape[:2]
    u = cv2.resize(flow[:, :, 0], (new_w, new_h)) * (new_w / float(w))
    v = cv2.resize(flow[:, :, 1], (new_w, new_h)) * (new_h / float(h))
    return np.dstack((u, v))
