"""Profiling: parameter tables and FLOP counting.

TPU-native replacement for the reference's flops mode (reference
infer_raft.py:80-95: tensorpack describe_trainable_vars + tf.profiler —
which crashed on an arity bug before ever printing, SURVEY.md §3.3).
Here: pytree param census + XLA ``cost_analysis`` on the compiled forward.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import numpy as np


def param_table(params, prefix: str = "") -> str:
    """Human-readable table of every leaf: path, shape, #params.

    Edge cases that must not crash the flops CLI: an empty pytree ({} or
    None) renders a TOTAL-0 table; scalar leaves — 0-d arrays AND plain
    Python numbers, which have no ``.shape`` — count as 1 parameter."""
    rows = []
    total = 0
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        shape = tuple(np.shape(leaf))     # () for scalars of any kind
        n = int(np.prod(shape)) if shape else 1
        total += n
        rows.append((prefix + name, str(shape), n))
    width = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'name':<{width}}{'shape':<20}{'#':>12}"]
    lines += [f"{n:<{width}}{s:<20}{c:>12,}" for n, s, c in rows]
    lines.append(f"{'TOTAL':<{width}}{'':<20}{total:>12,}")
    return "\n".join(lines)


def count_params(params) -> int:
    # np.shape (not .shape) so Python-scalar leaves count as 1, matching
    # param_table; np.prod(()) == 1.0 handles 0-d arrays
    return sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))


def _normalize_costs(costs) -> Dict[str, float]:
    """``compiled.cost_analysis()`` is backend-dependent: None (no analysis
    on this backend), a per-device list (possibly empty), or a dict that
    may omit any key.  Normalize all of that to a plain (possibly empty)
    {name: float} dict so callers only handle one shape."""
    if isinstance(costs, (list, tuple)):   # older jax: per-device list
        costs = costs[0] if costs else None
    if not costs:                          # None or {}
        return {}
    return {k: float(v) for k, v in costs.items()
            if k in ("flops", "bytes accessed", "optimal_seconds")}


def cost_analysis(fn: Callable, *args) -> Dict[str, float]:
    """XLA cost analysis of the jitted ``fn(*args)``: flops, bytes accessed
    — {} when the backend provides no analysis.

    Note XLA counts a multiply-add as 2 flops (same caveat the reference
    logged about tf.profiler, infer_raft.py:93-95).
    """
    lowered = jax.jit(fn).lower(*args)
    compiled = lowered.compile()
    return _normalize_costs(compiled.cost_analysis())


def flops_report(fn: Callable, *args) -> Tuple[float, str]:
    costs = cost_analysis(fn, *args)
    flops = costs.get("flops", float("nan"))
    return flops, (f"total flops: {flops:,.0f}  "
                   f"(XLA counts multiply+add as 2 flops)")
