from .flow_io import (read_flo, read_flow_any, read_kitti_flow, read_pfm,
                      readFlow, resize_flow, write_flo, write_kitti_flow,
                      write_pfm, writeFlow)
from .flow_viz import flow_compute_color, flow_to_color, make_colorwheel
from .frame_utils import (ReversedFlow, aug_img, calc_flow, erode_mask,
                          reverse_flow, set_static_flow)
from .profiling import cost_analysis, count_params, flops_report, param_table
