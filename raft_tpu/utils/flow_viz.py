"""Optical-flow visualization: the standard Middlebury color wheel
(Baker et al., ICCV 2007), as in reference flow_utils.py:6-121.

Implemented vectorized over the whole image (single fancy-indexing pass over
the wheel instead of the reference's per-channel Python loop).
"""

from __future__ import annotations

import numpy as np

_SEGMENTS = ((15, 0, 1, False),   # RY: R=255, G ramps up
             (6, 0, 1, True),     # YG: R ramps down, G=255
             (4, 1, 2, False),    # GC: G=255, B ramps up
             (11, 1, 2, True),    # CB: G ramps down, B=255
             (13, 2, 0, False),   # BM: B=255, R ramps up
             (6, 2, 0, True))     # MR: B ramps down, R=255


def make_colorwheel() -> np.ndarray:
    """[55, 3] RGB color wheel."""
    ncols = sum(s[0] for s in _SEGMENTS)
    wheel = np.zeros((ncols, 3))
    col = 0
    for n, full_ch, ramp_ch, down in _SEGMENTS:
        ramp = np.floor(255 * np.arange(n) / n)
        wheel[col:col + n, full_ch] = 255
        wheel[col:col + n, ramp_ch] = 255 - ramp if down else ramp
        col += n
    return wheel


def flow_compute_color(u: np.ndarray, v: np.ndarray,
                       convert_to_bgr: bool = False) -> np.ndarray:
    """Color an already max-normalized flow (|uv| <= 1 in-range)."""
    wheel = make_colorwheel()
    ncols = wheel.shape[0]

    rad = np.sqrt(u ** 2 + v ** 2)
    angle = np.arctan2(-v, -u) / np.pi                     # [-1, 1]
    fk = (angle + 1.0) / 2.0 * (ncols - 1) + 1.0
    k0 = np.minimum(np.floor(fk).astype(np.int32), ncols - 2)
    k1 = k0 + 1
    k1[k1 == ncols] = 1
    f = (fk - k0)[..., None]

    # divide-first order matters: it keeps floor(255*col) bit-identical to the
    # canonical Middlebury implementation at exact-255 edges
    col = (1.0 - f) * (wheel[k0] / 255.0) + f * (wheel[k1] / 255.0)   # [H, W, 3]
    in_range = (rad <= 1.0)[..., None]
    col = np.where(in_range, 1.0 - rad[..., None] * (1.0 - col), col * 0.75)

    img = np.floor(255.0 * col).astype(np.uint8)
    return img[..., ::-1] if convert_to_bgr else img


def flow_to_color(flow_uv: np.ndarray, clip_flow: float | None = None,
                  convert_to_bgr: bool = False) -> np.ndarray:
    """[H, W, 2] flow -> [H, W, 3] uint8 color image, normalized by max radius."""
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, flow_uv.shape
    flow = np.asarray(flow_uv, dtype=np.float64)
    if clip_flow is not None:
        flow = np.clip(flow, 0, clip_flow)
    u, v = flow[..., 0], flow[..., 1]
    rad_max = float(np.sqrt(u ** 2 + v ** 2).max(initial=0.0))
    eps = 1e-5
    return flow_compute_color(u / (rad_max + eps), v / (rad_max + eps), convert_to_bgr)
